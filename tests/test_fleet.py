"""Elastic fleet control loop (ISSUE 13): burn-rate-driven scale-out/in
with chaos-proof controller leasing.

The acceptance pins (via meshnet.chaos.ChaosController):

- deterministic lease arithmetic: claims order by (epoch, holder), a
  lapsed lease is taken over, a split-brain tie resolves to exactly one
  leader on both sides, and replica actions are epoch-gated;
- scale OUT is probe-gated: a standby walks standby → warming →
  (probe) → eligible, the router and migration plane never touch it
  before the flip, and a failed probe rolls it back to standby;
- scale IN drains the telemetry-worst node down the existing
  drain+migrate path and converts it to a warm standby;
- chaos: a leader killed mid-drain (or partitioned away) never strands
  the draining node — the successor adopts the orphan to completion or
  rolls it back when the fleet needs the capacity — and no in-flight
  generation is dropped anywhere in the matrix.

Model-free: FakeService fleets (the token-level drain/migrate story is
pinned by tests/test_migration.py; this file pins the CONTROL loop).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import time

import pytest

from bee2bee_tpu.fleet import FleetConfig, parse_fleet_config
from bee2bee_tpu.fleet.lease import LeaseKeeper, LeaseView, lease_beats
from bee2bee_tpu.health import (
    SloTracker,
    controller_aggregates,
    get_recorder,
    parse_slo_config,
)
from bee2bee_tpu.meshnet.chaos import ChaosController, hard_kill
from bee2bee_tpu.meshnet.node import P2PNode
from bee2bee_tpu.metrics import get_registry
from bee2bee_tpu.services.fake import FakeService
from tests.test_meshnet import _settle

MODEL = "fleet-model"
REPLY = "fleet reply " * 16  # long enough to stream across chunks

# a latency objective every FakeService call violates (exec_delay_s
# above threshold_ms), over the histogram FakeService actually observes
SLOW_SLO = [{
    "name": "exec_p95", "kind": "latency", "metric": "service.execute_ms",
    "threshold_ms": 16.0, "target": 0.95,
}]


def _cfg(**over) -> FleetConfig:
    """Test-cadence controller config (ticks ride a 0.1 s ping)."""
    base = dict(
        model=MODEL, min_replicas=1, max_replicas=8,
        # scale-in DISABLED by default (an idle loopback fleet would
        # otherwise start draining mid-test); the scale-in tests opt in
        out_sustain_ticks=2, in_sustain_ticks=10_000,
        scale_out_cooldown_s=0.5, scale_in_cooldown_s=0.5,
        ack_timeout_s=2.0, settle_timeout_s=2.0, probe_timeout_s=5.0,
        action_timeout_s=8.0, lease_ttl_s=0.4, claim_stagger_s=0.15,
        # the queue-wait HISTOGRAM is cumulative and process-global:
        # engine tests earlier in the suite leave a real p95 there that
        # no fleet in this file drives — it must never veto headroom
        headroom_queue_p95_ms=1e12,
    )
    base.update(over)
    return FleetConfig(**base)


@contextlib.asynccontextmanager
async def _fleet(controllers=1, actives=1, standbys=0, cfg=None,
                 slow_slo=False, exec_delay=0.0, stream_delay=0.0):
    """Loopback fleet: `controllers` lease-competing serving nodes,
    `actives` plain serving nodes, `standbys` warm standbys (service
    loaded + announced, digest-excluded). All on a 0.1 s ping cadence
    with digests gossiped and settled."""
    cfg = cfg or _cfg()
    # loopback fleets share the ONE process registry with every engine
    # test that ran before this file: stale batch-fill/row/pool gauges
    # would read as fake load (vetoing headroom) or fake live rows
    # (wedging drain quiescence). FakeService fleets drive none of these
    # — clear them so the digests say what THIS fleet is doing.
    for name in ("engine.batch_fill", "engine.active_rows",
                 "engine.paged_blocks_in_use", "engine.paged_blocks_free",
                 "engine.paged_blocks_total"):
        m = get_registry().get(name)
        if m is not None and hasattr(m, "clear"):
            m.clear()
    nodes, ctrls, acts, stands = [], [], [], []
    try:
        for i in range(controllers + actives + standbys):
            is_ctrl = i < controllers
            is_standby = i >= controllers + actives
            node = P2PNode(
                host="127.0.0.1", port=0,
                fleet_controller=is_ctrl,
                fleet_state="standby" if is_standby else None,
            )
            node.ping_interval_s = 0.1
            node.health.ttl_s = 1.5
            node.fleet.config = cfg
            node.fleet.lease.ttl_s = cfg.lease_ttl_s
            if slow_slo:
                node.slo = SloTracker(
                    objectives=parse_slo_config(SLOW_SLO),
                    fast_window_s=1.0, slow_window_s=5.0,
                )
            await node.start()
            svc = FakeService(
                MODEL, reply=REPLY, chunk_size=8,
                exec_delay_s=exec_delay, delay_s=stream_delay,
            )
            node.add_service(svc)
            nodes.append(node)
            (ctrls if is_ctrl else stands if is_standby else acts).append(node)
        for node in nodes[1:]:
            assert await node.connect_bootstrap(nodes[0].addr)
        n = len(nodes)
        assert await _settle(
            lambda: all(len(x.peers) == n - 1 for x in nodes), timeout=10
        )
        for node in nodes:
            await node.announce_service(node.local_services["fake"])
        for node in nodes:
            await node.gossip_telemetry()
        assert await _settle(
            lambda: all(len(x.health.fresh()) == n - 1 for x in nodes),
            timeout=10,
        )
        yield nodes, ctrls, acts, stands
    finally:
        for node in nodes:
            with contextlib.suppress(Exception):
                await node.stop()


async def _settle_leader(ctrls, timeout=10.0):
    """Exactly one leader AND every other controller has observed its
    lease — later epoch arithmetic is deterministic only once the reign
    is actually known fleet-wide."""
    chaos = ChaosController(ctrls)

    def converged():
        leaders = chaos.leaders()
        if len(leaders) != 1:
            return False
        holder = leaders[0].peer_id
        for c in ctrls:
            if c is leaders[0] or c._stopped:
                continue
            cur = c.fleet.lease.current()
            if cur is None or cur.holder != holder:
                return False
        return True

    assert await _settle(converged, timeout=timeout), (
        f"leaders: {[c.peer_id for c in chaos.leaders()]}"
    )
    return chaos.leader()


def _drive_load(node, stop: asyncio.Event, interval=0.05) -> asyncio.Task:
    """Background open-loop load through the node's own serving path —
    keeps the (shared-registry) SLO histograms burning until `stop`."""
    async def loop():
        while not stop.is_set():
            with contextlib.suppress(Exception):
                await node.request_generation(
                    node.peer_id, "burn", model=MODEL, max_new_tokens=8
                )
            await asyncio.sleep(interval)

    return asyncio.create_task(loop())


class _CaptureWs:
    """Fake ws: collects frames node._send writes at it."""

    def __init__(self):
        self.sent: list[dict] = []

    async def send(self, raw):
        self.sent.append(json.loads(raw))


def _fake_peer(node, pid: str, controller: bool = False) -> _CaptureWs:
    """Register a capture ws as a live peer connection — fleet frame
    handlers resolve identity via node._peer_for(ws), so action/ack
    tests must speak from a REGISTERED connection. With ``controller``
    the peer also advertises fleet_controller in a fresh digest (the
    eligibility gate lease/action frames are vetted against)."""
    ws = _CaptureWs()
    node.peers[pid] = {"ws": ws, "addr": None, "last_seen": time.time()}
    if controller:
        node.health.update(pid, {"fleet_controller": True})
    return ws


def _acks(ws: _CaptureWs) -> list[dict]:
    """The fleet_ack frames the node wrote at ws (the monitor loop also
    pings registered peers — filter those out)."""
    return [f for f in ws.sent if f.get("type") == "fleet_ack"]


# ------------------------------------------------------------- lease units


def test_lease_ordering_is_total_and_deterministic():
    assert lease_beats(2, "node-b", 1, "node-a")  # higher epoch wins
    assert not lease_beats(1, "node-a", 2, "node-b")
    assert lease_beats(1, "node-a", 1, "node-b")  # tie → smaller id
    assert not lease_beats(1, "node-b", 1, "node-a")


def test_lease_keeper_observe_and_lapse():
    k = LeaseKeeper(ttl_s=10.0)
    v = k.observe({"holder": "node-a", "epoch": 1, "ttl_s": 10.0}, now=100.0)
    assert v.holder == "node-a" and k.highest_epoch == 1
    # a same-epoch larger id loses; a higher epoch wins
    v = k.observe({"holder": "node-b", "epoch": 1, "ttl_s": 10.0}, now=101.0)
    assert v.holder == "node-a"
    v = k.observe({"holder": "node-b", "epoch": 2, "ttl_s": 10.0}, now=102.0)
    assert v.holder == "node-b" and k.highest_epoch == 2
    # fresh within ttl, lapsed past it — lapse timed from expiry, not
    # from the poll
    assert k.current(now=111.9) is not None
    assert k.current(now=112.1) is None
    assert k.lapsed_for(now=114.0) == pytest.approx(2.0)
    # any live claim beats a dead reign, even a lower epoch from a
    # smaller... no: epoch floor still applies via authorizes; observe
    # replaces the lapsed view
    v = k.observe({"holder": "node-z", "epoch": 3, "ttl_s": 10.0}, now=115.0)
    assert v.holder == "node-z"
    # released zeroes the TTL
    k.observe({"holder": "node-z", "epoch": 3, "ttl_s": 10.0,
               "released": True}, now=116.0)
    assert k.current(now=116.1) is None


def test_lease_keeper_authorizes_epoch_gated():
    k = LeaseKeeper(ttl_s=10.0)
    # bootstrap: nothing observed → first claimant is trusted
    assert k.authorizes("node-a", 1, now=100.0)
    k.observe({"holder": "node-a", "epoch": 5, "ttl_s": 10.0}, now=100.0)
    assert not k.authorizes("node-b", 4, now=101.0)   # stale epoch
    assert k.authorizes("node-a", 5, now=101.0)       # the holder itself
    assert not k.authorizes("node-z", 5, now=101.0)   # tie lost to holder
    assert k.authorizes("node-0", 5, now=101.0)       # tie won (smaller id)
    assert k.authorizes("node-z", 6, now=101.0)       # higher epoch
    # junk never authorizes
    assert not k.authorizes("", 7, now=101.0)
    assert not k.authorizes("node-a", "junk", now=101.0)


def test_authorizes_follows_the_reinstalled_lower_epoch_reign():
    """A higher epoch observed once from a now-dead claimant must not
    permanently refuse the leader whose renewals we actively accept:
    once the higher reign lapses and the live lower-epoch holder is
    re-installed as current, its actions authorize again (the all-time
    epoch floor gates only lease-less claimants)."""
    k = LeaseKeeper(ttl_s=10.0)
    k.observe({"holder": "node-a", "epoch": 5, "ttl_s": 10.0}, now=100.0)
    # a partitioned rival claims epoch 6, then dies
    k.observe({"holder": "node-b", "epoch": 6, "ttl_s": 10.0}, now=101.0)
    assert not k.authorizes("node-a", 5, now=102.0)  # b's reign is fresh
    # b's lease lapses; a's ongoing renewal re-installs a as current
    k.observe({"holder": "node-a", "epoch": 5, "ttl_s": 10.0}, now=112.0)
    assert k.current(now=112.5).holder == "node-a"
    assert k.authorizes("node-a", 5, now=112.5), (
        "the recognized current holder must be authorized despite the "
        "lapsed higher epoch in history"
    )
    # but with NO fresh lease, the floor still gates claimants
    assert not k.authorizes("node-x", 5, now=130.0)
    assert k.authorizes("node-x", 6, now=130.0)


def test_lease_view_describe_roundtrip():
    v = LeaseView(holder="n", epoch=3, ttl_s=5.0, received_at=50.0)
    d = v.describe(now=51.0)
    assert d["holder"] == "n" and d["epoch"] == 3 and d["fresh"] is True
    assert d["age_s"] == pytest.approx(1.0)


# ------------------------------------------------------------ config units


def test_parse_fleet_config_validates_loudly():
    assert parse_fleet_config({"min_replicas": 2}).min_replicas == 2
    with pytest.raises(ValueError, match="unknown keys"):
        parse_fleet_config({"min_replica": 2})
    with pytest.raises(ValueError, match="must be a JSON object"):
        parse_fleet_config([1])
    with pytest.raises(ValueError, match="min_replicas > max_replicas"):
        parse_fleet_config({"min_replicas": 9, "max_replicas": 2})
    with pytest.raises(ValueError, match="burn_quorum"):
        parse_fleet_config({"burn_quorum": 0.0})
    with pytest.raises(ValueError, match=">= 0"):
        parse_fleet_config({"ack_timeout_s": -1})


def test_load_fleet_config_env(monkeypatch):
    from bee2bee_tpu.fleet import load_fleet_config

    monkeypatch.setenv("BEE2BEE_FLEET_CONFIG", '{"max_replicas": 3}')
    assert load_fleet_config().max_replicas == 3
    monkeypatch.setenv("BEE2BEE_FLEET_CONFIG", '{"bogus": 1}')
    with pytest.raises(ValueError):
        load_fleet_config()


# --------------------------------------------------------- decision units


def _controller_for_units(**over):
    node = P2PNode(host="127.0.0.1", port=0, fleet_controller=True)
    node.fleet.config = _cfg(in_sustain_ticks=3, **over)
    node.fleet.is_leader = True
    return node.fleet


def test_decide_hysteresis_sustain_and_cooldown():
    ctrl = _controller_for_units()
    burning = {
        "eligible": 2, "eligible_ids": ["a", "b"], "burning": 2,
        "burning_frac": 1.0, "fill_mean": 0.9, "queue_p95_max": 900.0,
    }
    standby_digests = {"s": {"fleet_state": "standby"}}
    # one burning tick is a blip, not a trend
    d, _, _ = ctrl._decide(100.0, burning, standby_digests)
    assert d == "noop"
    d, _, t = ctrl._decide(100.1, burning, standby_digests)
    assert d == "scale_out" and t == "s"
    # cooldown: a just-completed action blocks the next
    ctrl._action = {"kind": "scale_out", "target": "s"}
    ctrl._finish_action(True, "fleet:scale_out", "unit")
    ctrl._burn_streak = 5
    d, reason, _ = ctrl._decide(100.2, burning, standby_digests)
    assert d == "noop" and "cooldown" in reason
    # bounds: at max_replicas burning never scales out
    ctrl2 = _controller_for_units()
    ctrl2._burn_streak = 5
    maxed = {**burning, "eligible": ctrl2.config.max_replicas}
    d, reason, _ = ctrl2._decide(200.0, maxed, standby_digests)
    assert d == "noop" and "max_replicas" in reason
    # no standby → burning stays a noop, loudly
    ctrl3 = _controller_for_units()
    ctrl3._burn_streak = 5
    d, reason, _ = ctrl3._decide(300.0, burning, {})
    assert d == "noop" and "no standby" in reason


def test_decide_repairs_below_min_replicas_without_burn():
    """A dead replica reports no burn — the floor itself must trigger
    the scale-out, with no sustain window (capacity is already gone)."""
    ctrl = _controller_for_units(min_replicas=2)
    dead_fleet = {
        "eligible": 1, "eligible_ids": ["a"], "burning": 0,
        "burning_frac": 0.0, "fill_mean": 0.0, "queue_p95_max": 0.0,
    }
    standby_digests = {"s": {"fleet_state": "standby"}}
    d, reason, target = ctrl._decide(100.0, dead_fleet, standby_digests)
    assert d == "scale_out" and target == "s" and "repair" in reason
    # without a standby it is a loud noop, not silence
    d, reason, _ = ctrl._decide(100.1, dead_fleet, {})
    assert d == "noop" and "below min_replicas" in reason


def test_decide_scale_in_needs_sustained_headroom_and_remote_target():
    ctrl = _controller_for_units()
    me = ctrl.node.peer_id
    idle = {
        "eligible": 3, "eligible_ids": sorted([me, "node-x", "node-y"]),
        "burning": 0, "burning_frac": 0.0, "fill_mean": 0.0,
        "queue_p95_max": 0.0,
    }
    digests = {"node-x": {}, "node-y": {}}
    for i in range(ctrl.config.in_sustain_ticks - 1):
        d, _, _ = ctrl._decide(100.0 + i, idle, digests)
        assert d == "noop"
    d, _, target = ctrl._decide(110.0, idle, digests)
    assert d == "scale_in" and target in ("node-x", "node-y")
    # min_replicas floor
    ctrl2 = _controller_for_units()
    ctrl2._headroom_streak = 99
    floor = {**idle, "eligible": ctrl2.config.min_replicas}
    d, reason, _ = ctrl2._decide(100.0, floor, digests)
    assert d == "noop" and "min_replicas" in reason
    # never drains itself: only the local node eligible → no candidate
    ctrl3 = _controller_for_units()
    ctrl3._headroom_streak = 99
    me3 = ctrl3.node.peer_id
    solo = {**idle, "eligible": 2, "eligible_ids": [me3, "zz-remote"]}
    d, _, target = ctrl3._decide(100.0, solo, {"zz-remote": {}})
    assert d == "scale_in" and target == "zz-remote"


def test_pick_worst_is_highest_router_penalty():
    ctrl = _controller_for_units()
    agg = {"eligible_ids": ["node-hot", "node-cool"]}
    digests = {
        "node-hot": {"hist": {"engine.queue_wait_ms": {"p95": 5000.0}},
                     "gauge": {"engine.batch_fill": 1.0}},
        "node-cool": {"gauge": {"engine.batch_fill": 0.0}},
    }
    assert ctrl._pick_worst(agg, digests) == "node-hot"


# ------------------------------------------------- routing exclusion units


def test_router_never_routes_to_standby_or_warming():
    from bee2bee_tpu.router.policy import RouterPolicy

    policy = RouterPolicy()
    cands = [
        {"provider_id": "warm", "local": False},
        {"provider_id": "live", "local": False},
    ]
    fresh = {"warm": {"fleet_state": "warming"}, "live": {}}
    winner, decision = policy.pick(cands, fresh)
    assert winner["provider_id"] == "live"
    # an unprobed replica is excluded even when it is the ONLY candidate
    # (no all-burning-style waiver — better no pick than an unprobed one)
    winner, _ = policy.pick(cands[:1], fresh)
    assert winner is None
    fresh["warm"]["fleet_state"] = "standby"
    winner, _ = policy.pick(cands[:1], fresh)
    assert winner is None


async def test_migration_targets_exclude_unprobed_replicas():
    async with _fleet(controllers=0, actives=2) as (nodes, _, acts, _s):
        a, b = acts
        assert b.peer_id in a.migration.migration_targets(MODEL)
        # b flips to warming: it must stop being a migration target on
        # the next gossip — live state is traffic too
        b.fleet_state = "warming"
        await b.gossip_telemetry()
        assert await _settle(
            lambda: b.peer_id not in a.migration.migration_targets(MODEL),
            timeout=5,
        )


# -------------------------------------------------------- live fleet tests


@pytest.mark.async_timeout(120)
async def test_single_controller_claims_and_journals_noops():
    async with _fleet(controllers=1, actives=1) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        assert leader is ctrls[0]
        assert await _settle(
            lambda: any(
                d["decision"] == "noop" for d in leader.fleet.decisions
            ),
            timeout=10,
        )
        # the follower holds the leader's lease view
        assert await _settle(
            lambda: (
                acts[0].fleet.lease.current() is not None
                and acts[0].fleet.lease.current().holder == leader.peer_id
            ),
            timeout=10,
        )
        st = leader.fleet.status()
        assert st["is_leader"] and st["lease"]["holder"] == leader.peer_id
        assert st["aggregates"].get("eligible") == 2


@pytest.mark.async_timeout(120)
async def test_leader_death_deterministic_takeover():
    recorder = get_recorder()
    recorder.clear()
    async with _fleet(controllers=2, actives=1) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        epoch0 = leader.fleet.epoch
        other = next(c for c in ctrls if c is not leader)
        await hard_kill(leader)
        assert await _settle(lambda: other.fleet.is_leader, timeout=15), (
            "the surviving controller never took over the lapsed lease"
        )
        assert other.fleet.epoch > epoch0  # a takeover is a NEW reign
        recorder.flush()
        kinds = {e["kind"] for e in recorder.list_incidents()}
        assert "fleet:takeover" in kinds


@pytest.mark.async_timeout(120)
async def test_split_brain_tie_resolves_to_smaller_peer_id():
    async with _fleet(controllers=2, actives=0) as (nodes, ctrls, _a, _s):
        leader = await _settle_leader(ctrls)
        other = next(c for c in ctrls if c is not leader)
        chaos = ChaosController(ctrls)
        # force a genuine double-leader at the SAME epoch
        await chaos.usurp(other, epoch=leader.fleet.epoch)
        assert await _settle(lambda: len(chaos.leaders()) == 1, timeout=15)
        winner = chaos.leader()
        assert winner.peer_id == min(c.peer_id for c in ctrls), (
            "equal-epoch split-brain must resolve to the smaller peer id"
        )
        # the loser stepped down explicitly, not by timeout
        loser = next(c for c in ctrls if c is not winner)
        assert loser.fleet.stats["stepdowns"] >= 1


@pytest.mark.async_timeout(120)
async def test_lease_partition_heals_to_single_leader():
    async with _fleet(controllers=2, actives=0) as (nodes, ctrls, _a, _s):
        leader = await _settle_leader(ctrls)
        other = next(c for c in ctrls if c is not leader)
        chaos = ChaosController(ctrls)
        # the nasty split: telemetry still flows, leadership is invisible
        chaos.partition(leader, other)
        assert await _settle(lambda: other.fleet.is_leader, timeout=15), (
            "the partitioned follower never claimed the invisible lease"
        )
        assert len(chaos.leaders()) == 2  # AP by design during the split
        assert other.fleet.epoch > leader.fleet.epoch
        chaos.heal()
        # on heal the higher epoch wins on BOTH sides
        assert await _settle(
            lambda: len(chaos.leaders()) == 1
            and chaos.leader() is other,
            timeout=15,
        )
        assert leader.fleet.stats["stepdowns"] >= 1


@pytest.mark.async_timeout(120)
async def test_stale_epoch_action_is_refused():
    async with _fleet(controllers=0, actives=1) as (nodes, _c, acts, _s):
        b = acts[0]
        b.fleet.lease.observe(
            {"holder": "node-000leader", "epoch": 5, "ttl_s": 30.0}
        )
        stale_ws = _fake_peer(b, "node-zzz-stale", controller=True)
        leader_ws = _fake_peer(b, "node-000leader", controller=True)
        await b.fleet.on_action(stale_ws, {
            "rid": "r1", "action": "drain", "epoch": 4,
            "holder": "node-zzz-stale",
        })
        acks = _acks(stale_ws)
        assert acks and acks[0]["ok"] is False
        assert acks[0]["error"] == "stale_epoch"
        assert b.draining is False  # the stale command changed nothing
        # the rightful holder's command lands
        await b.fleet.on_action(leader_ws, {
            "rid": "r2", "action": "drain", "epoch": 5,
            "holder": "node-000leader",
        })
        assert _acks(leader_ws)[-1]["ok"] is True and b.draining is True


@pytest.mark.async_timeout(120)
async def test_forged_holder_action_is_dropped():
    """A connected peer that copies the gossiped leader identity (with
    an arbitrarily high epoch) must neither command the node nor poison
    its lease view / epoch floor: on_action binds the claimed holder to
    the sending connection, exactly like on_lease."""
    async with _fleet(controllers=0, actives=1) as (nodes, _c, acts, _s):
        b = acts[0]
        b.fleet.lease.observe(
            {"holder": "node-000leader", "epoch": 5, "ttl_s": 30.0}
        )
        # evil IS controller-eligible here, so this pins the holder
        # binding specifically (eligibility alone would not save us)
        evil_ws = _fake_peer(b, "node-evil", controller=True)
        await b.fleet.on_action(evil_ws, {
            "rid": "rf", "action": "drain", "epoch": 10_000,
            "holder": "node-000leader",
        })
        # dropped silently: no ack, no drain, no epoch-floor bump —
        # the rightful leader's reign stays intact
        assert not _acks(evil_ws)
        assert b.draining is False
        assert b.fleet.lease.highest_epoch == 5
        cur = b.fleet.lease.current()
        assert cur is not None and cur.holder == "node-000leader"
        # a connection that is not a known peer at all is dropped too
        await b.fleet.on_action(_CaptureWs(), {
            "rid": "rg", "action": "drain", "epoch": 5,
            "holder": "node-000leader",
        })
        assert b.draining is False


@pytest.mark.async_timeout(120)
async def test_non_controller_self_claim_is_refused():
    """Connection binding alone is not enough: a plain serving peer
    self-claiming an invented high epoch under its OWN identity must
    not command the node either — lease and action frames only count
    from peers whose fresh digest advertises fleet_controller."""
    async with _fleet(controllers=0, actives=1) as (nodes, _c, acts, _s):
        b = acts[0]
        b.fleet.lease.observe(
            {"holder": "node-000leader", "epoch": 5, "ttl_s": 30.0}
        )
        rogue_ws = _fake_peer(b, "node-rogue")  # NOT controller-eligible
        await b.fleet.on_action(rogue_ws, {
            "rid": "rr", "action": "drain", "epoch": 10_000,
            "holder": "node-rogue",
        })
        acks = _acks(rogue_ws)
        assert acks and acks[0]["ok"] is False
        assert acks[0]["error"] == "not_controller"
        assert b.draining is False
        assert b.fleet.lease.highest_epoch == 5  # floor unpoisoned
        # its lease claims are dropped too — the recognized reign and
        # the epoch floor both stay with the rightful leader
        await b.fleet.on_lease(rogue_ws, {
            "holder": "node-rogue", "epoch": 10_000, "ttl_s": 30.0,
        })
        cur = b.fleet.lease.current()
        assert cur is not None and cur.holder == "node-000leader"
        assert b.fleet.lease.highest_epoch == 5


@pytest.mark.async_timeout(120)
async def test_forged_ack_is_ignored():
    """A FLEET_ACK only completes an action when it arrives over the
    connection the action went out on — another peer replaying the rid
    cannot fake a drain/activate completion."""
    async with _fleet(controllers=0, actives=1) as (nodes, _c, acts, _s):
        b = acts[0]
        target_ws = _fake_peer(b, "node-target")
        evil_ws = _fake_peer(b, "node-evil")
        task = asyncio.create_task(
            b.fleet.send_action("node-target", "undrain", timeout=5.0)
        )
        assert await _settle(lambda: bool(b.fleet._acks), timeout=2)
        rid = next(iter(b.fleet._acks))
        await b.fleet.on_ack(evil_ws, {"rid": rid, "ok": True})
        _, _, fut = b.fleet._acks[rid]
        assert not fut.done()  # the forged ack changed nothing
        await b.fleet.on_ack(target_ws, {"rid": rid, "ok": True})
        ack = await task
        assert ack["ok"] is True


def test_lease_keeper_boot_grace_before_first_claim():
    k = LeaseKeeper(ttl_s=10.0)
    k._lapse_started = 100.0  # the boot instant, on the fake clock
    # nothing ever observed: one full TTL of silence must pass before
    # the void counts as a lapse, so a freshly booted node cannot claim
    # (and usurp a live incumbent) before the incumbent's gossip arrives
    assert k.lapsed_for(now=100.0) is None
    assert k.lapsed_for(now=109.9) is None
    assert k.lapsed_for(now=112.0) == pytest.approx(2.0)
    # once a lease HAS been observed the grace never applies again:
    # lapse counts straight from the TTL expiry
    k.observe({"holder": "node-a", "epoch": 1, "ttl_s": 10.0}, now=112.0)
    assert k.lapsed_for(now=121.0) is None
    assert k.lapsed_for(now=124.0) == pytest.approx(2.0)


def test_lease_boot_grace_re_anchors_at_mesh_join():
    # construction→start can take longer than a TTL (first jit compile,
    # retried bootstrap): node.start() re-anchors the grace so it is
    # not silently consumed before the node has even joined the mesh
    k = LeaseKeeper(ttl_s=10.0)
    k._lapse_started = 50.0  # constructed long ago on the fake clock
    assert k.lapsed_for(now=100.0) == pytest.approx(40.0)  # grace eaten
    k.reset_boot_grace(now=100.0)  # the node actually starts here
    assert k.lapsed_for(now=105.0) is None
    assert k.lapsed_for(now=112.0) == pytest.approx(2.0)
    # once a lease is held, re-anchoring is a no-op (restarting the
    # monitor loop must not erase a known reign's lapse bookkeeping)
    k.observe({"holder": "node-a", "epoch": 1, "ttl_s": 10.0}, now=112.0)
    k.reset_boot_grace(now=500.0)
    assert k.current(now=113.0) is not None


def test_lease_boot_grace_deferral_is_capped():
    # a rolling bootstrap — or a crash-looping peer minting a fresh
    # random id per restart — keeps re-anchoring the grace on every
    # first contact; the first claim must still be bounded (three TTLs
    # past the first anchor), or the fleet stays leaderless forever
    k = LeaseKeeper(ttl_s=10.0)
    k.reset_boot_grace(now=100.0)  # node start: anchor cap = 120
    for t in (109.0, 118.0, 127.0, 136.0):  # endless fresh peer ids
        k.reset_boot_grace(now=t)
    # the anchor clamps at 120 → the grace ends at 130, not at 146
    assert k.lapsed_for(now=129.0) is None
    assert k.lapsed_for(now=132.0) == pytest.approx(2.0)


@pytest.mark.async_timeout(180)
async def test_burn_scale_out_probes_then_flips_standby_eligible():
    recorder = get_recorder()
    recorder.clear()
    async with _fleet(
        controllers=1, actives=1, standbys=1,
        slow_slo=True, exec_delay=0.05,
    ) as (nodes, ctrls, acts, stands):
        leader = await _settle_leader(ctrls)
        standby = stands[0]
        # while standby: never routable, in the standby bucket
        prov = acts[0].pick_provider(MODEL, remote_only=True)
        assert prov is not None and prov["provider_id"] != standby.peer_id
        stop = asyncio.Event()
        load = _drive_load(leader, stop)
        try:
            assert await _settle(
                lambda: standby.fleet_state is None, timeout=60
            ), (
                f"standby never became eligible; journal: "
                f"{list(leader.fleet.decisions)[-5:]}"
            )
        finally:
            stop.set()
            with contextlib.suppress(Exception):
                await load
        assert leader.fleet.stats["scale_out"] == 1
        recorder.flush()
        kinds = {e["kind"] for e in recorder.list_incidents()}
        assert "fleet:scale_out" in kinds
        # the probe generation actually served on the replica
        assert any(
            c.get("prompt") == leader.fleet.config.probe_prompt
            for c in standby.local_services["fake"].calls
        ), "replica flipped eligible without serving the warm-up probe"


@pytest.mark.async_timeout(180)
async def test_provision_probe_failure_rolls_back_to_standby():
    recorder = get_recorder()
    recorder.clear()
    async with _fleet(controllers=1, actives=0, standbys=1) as (
        nodes, ctrls, _a, stands,
    ):
        leader = await _settle_leader(ctrls)
        standby = stands[0]
        chaos = ChaosController([leader])
        chaos.fail_probe(leader, fails=1)
        try:
            out = await leader.fleet.override("scale_out")
            assert out["ok"], out
            assert await _settle(
                lambda: leader.fleet._action is None, timeout=30
            )
        finally:
            chaos.restore()
        assert standby.fleet_state == "standby", (
            "a replica that failed its probe must return to standby"
        )
        assert leader.fleet.stats["scale_out"] == 0
        assert leader.fleet.stats["provision_failed"] == 1
        recorder.flush()
        kinds = {e["kind"] for e in recorder.list_incidents()}
        assert "fleet:provision_failed" in kinds
        # stats pin that NO scale-out completed this test (disk bundles
        # persist across tests, so the negative is asserted off stats)


@pytest.mark.async_timeout(180)
async def test_headroom_scale_in_drains_worst_to_standby():
    recorder = get_recorder()
    recorder.clear()
    async with _fleet(
        controllers=1, actives=2,
        cfg=_cfg(min_replicas=2, in_sustain_ticks=3),
        stream_delay=0.02,
    ) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        # the worst-node pick weighs per-peer RTT, so either active may
        # be chosen — pin the INVARIANTS, not the victim: in-flight
        # generations on BOTH candidates must complete untouched (zero
        # dropped generations, whichever one drains)
        streams = [
            asyncio.create_task(a.request_generation(
                a.peer_id, "inflight", model=MODEL,
                max_new_tokens=64, stream=True, on_chunk=lambda _t: None,
            ))
            for a in acts
        ]
        assert await _settle(
            lambda: any(a.fleet_state == "standby" for a in acts),
            timeout=60,
        ), f"journal: {list(leader.fleet.decisions)[-5:]}"
        drained = next(a for a in acts if a.fleet_state == "standby")
        survivor = next(a for a in acts if a is not drained)
        assert survivor.fleet_state is None  # exactly one scaled in
        assert drained.draining is False, (
            "scale-in left the node draining instead of standby"
        )
        for result in [await s for s in streams]:
            assert result["text"] == REPLY
        assert leader.fleet.stats["scale_in"] == 1
        # at min_replicas now: the loop must hold, not flap
        agg = leader.fleet.status()["aggregates"]
        assert agg.get("eligible") == 2
        recorder.flush()
        kinds = {e["kind"] for e in recorder.list_incidents()}
        assert "fleet:scale_in" in kinds


@pytest.mark.async_timeout(180)
async def test_leader_killed_mid_drain_successor_adopts_orphan():
    """THE chaos acceptance walk: the leader dies while its scale-in
    drain is in flight (the target still has live rows). The successor
    takes over the lapsed lease, finds the orphaned draining peer in the
    digests, adopts the drain to completion — and the in-flight
    generation on the target completes. Nothing is stranded, nothing is
    dropped."""
    recorder = get_recorder()
    recorder.clear()
    rows = get_registry().gauge(
        "engine.active_rows", "live engine batch rows"
    )
    async with _fleet(
        controllers=2, actives=2, cfg=_cfg(min_replicas=1, action_timeout_s=30.0),
        stream_delay=0.05,
    ) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        successor = next(c for c in ctrls if c is not leader)
        target = acts[0]
        try:
            # live rows pin the drain in its awaiting-quiesce phase
            # (loopback nodes share one registry, so every digest shows
            # them — which is exactly what holds _await_drained open)
            rows.set(2.0)
            chunks: list[str] = []
            stream = asyncio.create_task(target.request_generation(
                target.peer_id, "inflight", model=MODEL,
                max_new_tokens=64, stream=True, on_chunk=chunks.append,
            ))
            out = await leader.fleet.override(
                "scale_in", target=target.peer_id
            )
            assert out["ok"], out
            assert await _settle(lambda: target.draining, timeout=10)
            assert leader.fleet._action is not None
            await hard_kill(leader)  # mid-drain, action in flight
            assert await _settle(
                lambda: successor.fleet.is_leader, timeout=15
            )
            # the successor adopts the orphaned drain (fleet is idle —
            # no capacity pressure, so adoption, not rollback)
            assert await _settle(
                lambda: successor.fleet._action is not None
                or target.fleet_state == "standby",
                timeout=15,
            )
            result = await stream  # zero dropped generations
            assert result["text"] == REPLY
            rows.clear()  # the live work finished; drain can quiesce
            assert await _settle(
                lambda: target.fleet_state == "standby"
                and not target.draining,
                timeout=30,
            ), "orphaned drain was neither completed nor rolled back"
        finally:
            rows.clear()
        assert successor.fleet.stats["adopted"] >= 1
        recorder.flush()
        kinds = {e["kind"] for e in recorder.list_incidents()}
        assert "fleet:takeover" in kinds
        assert "fleet:drain_adopted" in kinds
        assert "fleet:scale_in" in kinds


@pytest.mark.async_timeout(180)
async def test_orphaned_drain_rolled_back_when_fleet_burning():
    """The other adoption branch: the fleet is burning, so the orphaned
    drain's capacity is NEEDED — the new leader rolls it back (undrain)
    instead of completing the scale-in."""
    recorder = get_recorder()
    recorder.clear()
    async with _fleet(
        controllers=1, actives=1, slow_slo=True, exec_delay=0.05,
    ) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        target = acts[0]
        stop = asyncio.Event()
        load = _drive_load(leader, stop)
        try:
            # wait until the leader's own view says the fleet burns
            assert await _settle(
                lambda: (leader.fleet._last_agg or {}).get("burning", 0) > 0,
                timeout=30,
            )
            # a dead predecessor's FLEET drain left this node draining
            # (an operator drain would be left alone — separate test)
            target.draining = True
            target.drain_source = "fleet"
            await target.gossip_telemetry()
            assert await _settle(lambda: not target.draining, timeout=30), (
                "burning fleet never rolled the orphaned drain back"
            )
        finally:
            stop.set()
            with contextlib.suppress(Exception):
                await load
        assert target.fleet_state is None  # eligible again, not standby
        assert leader.fleet.stats["rolled_back"] >= 1
        recorder.flush()
        kinds = {e["kind"] for e in recorder.list_incidents()}
        assert "fleet:drain_rollback" in kinds


@pytest.mark.async_timeout(120)
async def test_operator_drain_is_never_reconciled_by_the_fleet():
    """A deliberate POST /admin/drain (drain_source="operator") is not
    the controller's state to fix: even a burning fleet must not undrain
    a node the operator is about to kill, and an idle one must not
    convert it to standby."""
    async with _fleet(
        controllers=1, actives=1, slow_slo=True, exec_delay=0.05,
    ) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        target = acts[0]
        stop = asyncio.Event()
        load = _drive_load(leader, stop)
        try:
            assert await _settle(
                lambda: (leader.fleet._last_agg or {}).get("burning", 0) > 0,
                timeout=30,
            )
            await target.begin_drain(wait=False)  # the operator's drain
            assert target.drain_source == "operator"
            await target.gossip_telemetry()
            # give the (burning) leader several ticks to take the bait
            await asyncio.sleep(1.0)
            assert target.draining is True, (
                "the controller undrained an operator's deliberate drain"
            )
            assert target.fleet_state is None
            assert leader.fleet.stats["rolled_back"] == 0
            assert leader.fleet.stats["adopted"] == 0
        finally:
            stop.set()
            with contextlib.suppress(Exception):
                await load


@pytest.mark.async_timeout(120)
async def test_dead_replica_below_min_is_repaired_from_standby():
    """min_replicas is a floor to RESTORE, not just a scale-in bound: a
    crashed replica's digest goes stale and vanishes — it reports no
    burn, so only the repair path can activate the warm standby."""
    async with _fleet(
        controllers=1, actives=1, standbys=1, cfg=_cfg(min_replicas=2),
    ) as (nodes, ctrls, acts, stands):
        leader = await _settle_leader(ctrls)
        standby = stands[0]
        # eligible = controller + active = min_replicas: steady state
        assert await _settle(
            lambda: (leader.fleet._last_agg or {}).get("eligible") == 2,
            timeout=10,
        )
        await hard_kill(acts[0])  # no drain flag, no burn — just gone
        assert await _settle(
            lambda: standby.fleet_state is None, timeout=30
        ), (
            f"standby never activated after the replica died; journal: "
            f"{list(leader.fleet.decisions)[-5:]}"
        )
        assert leader.fleet.stats["scale_out"] == 1


@pytest.mark.async_timeout(120)
async def test_orphaned_warming_replica_is_reprobed_or_returned():
    """A provision that died between activate and the probe leaves a
    warming node: the leader's orphan scan re-probes it to eligibility
    (never leaves it invisible capacity)."""
    async with _fleet(controllers=1, actives=1) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        orphan = acts[0]
        orphan.fleet_state = "warming"  # a dead controller's half-provision
        await orphan.gossip_telemetry()
        assert await _settle(
            lambda: orphan.fleet_state is None, timeout=30
        ), "orphaned warming replica was never re-probed to a terminal state"
        assert leader.fleet.stats["adopted"] >= 1
        # the re-probe really served
        assert any(
            c.get("prompt") == leader.fleet.config.probe_prompt
            for c in orphan.local_services["fake"].calls
        )


@pytest.mark.async_timeout(120)
async def test_fleet_endpoint_and_override():
    from aiohttp.test_utils import TestClient, TestServer

    from bee2bee_tpu.api import build_app

    async with _fleet(controllers=1, actives=1) as (nodes, ctrls, acts, _s):
        leader = await _settle_leader(ctrls)
        follower = acts[0]
        client = TestClient(TestServer(build_app(leader)))
        fclient = TestClient(TestServer(build_app(follower)))
        await client.start_server()
        await fclient.start_server()
        try:
            r = await client.get("/fleet")
            assert r.status == 200
            st = await r.json()
            assert st["is_leader"] is True
            assert st["lease"]["holder"] == leader.peer_id
            assert isinstance(st["decisions"], list)
            assert st["config"]["model"] == MODEL

            r = await client.post("/fleet/override", json={})
            assert r.status == 400
            r = await client.post(
                "/fleet/override", json={"action": "pause"}
            )
            assert r.status == 200 and leader.fleet.paused
            assert await _settle(
                lambda: any(
                    d["decision"] == "paused"
                    for d in leader.fleet.decisions
                ),
                timeout=10,
            )
            r = await client.post(
                "/fleet/override", json={"action": "resume"}
            )
            assert r.status == 200 and not leader.fleet.paused
            # scale overrides only run on the leader — 409 points at it
            r = await fclient.post(
                "/fleet/override", json={"action": "scale_in"}
            )
            assert r.status == 409
            body = await r.json()
            assert body["error"] == "not_leader"
            assert body["leader"] == leader.peer_id
            # no standby in this fleet: a forced scale_out is a typed 400
            r = await client.post(
                "/fleet/override", json={"action": "scale_out"}
            )
            assert r.status == 400
            assert "standby" in (await r.json())["error"]
        finally:
            await client.close()
            await fclient.close()


@pytest.mark.async_timeout(120)
async def test_mesh_health_serves_controller_aggregates():
    from aiohttp.test_utils import TestClient, TestServer

    from bee2bee_tpu.api import build_app

    async with _fleet(controllers=1, actives=1, standbys=1) as (
        nodes, ctrls, acts, stands,
    ):
        await _settle_leader(ctrls)
        client = TestClient(TestServer(build_app(acts[0])))
        await client.start_server()
        try:
            view = await (await client.get("/mesh/health")).json()
            fleet = view["aggregate"]["fleet"]
            assert stands[0].peer_id in fleet["standby"]
            assert fleet["nodes"] == 3
        finally:
            await client.close()


def test_controller_aggregates_pure_units():
    # bucketing: draining/standby/warming never count toward headroom,
    # a non-serving digest is "other" when a serving set is given
    digests = {
        "a": {"slo": {"o": {"status": "burning", "burn_fast": 12.0}},
              "gauge": {"engine.batch_fill": 0.8, "engine.active_rows": 3,
                        "engine.paged_blocks_total": 100,
                        "engine.paged_blocks_free": 10}},
        "b": {"gauge": {"engine.batch_fill": 0.2}},
        "c": {"draining": True, "gauge": {"engine.batch_fill": 0.0}},
        "d": {"fleet_state": "standby"},
        "e": {"fleet_state": "warming"},
        "f": {},  # gossiping client, not a replica
    }
    agg = controller_aggregates(
        digests, serving={"a", "b", "c", "d", "e"}
    )
    assert agg["nodes"] == 6
    assert agg["eligible"] == 2 and agg["eligible_ids"] == ["a", "b"]
    assert agg["draining"] == ["c"] and agg["standby"] == ["d"]
    assert agg["warming"] == ["e"] and agg["other"] == ["f"]
    assert agg["burning"] == 1 and agg["burning_frac"] == 0.5
    assert agg["burn_fast_max"] == 12.0
    assert agg["fill_mean"] == pytest.approx(0.5)
    assert agg["pool_free_min"] == pytest.approx(0.1)
    assert agg["active_rows_total"] == 3.0
    # empty eligible set: every rate degrades to zero, not a crash
    empty = controller_aggregates({"c": {"draining": True}})
    assert empty["eligible"] == 0 and empty["burning_frac"] == 0.0
    assert empty["pool_free_min"] is None
