"""InferenceEngine tests: streaming, determinism, sampling, truncation,
metrics — the contract the service layer builds on."""

import jax
import numpy as np
import pytest

from bee2bee_tpu.engine import EngineConfig, InferenceEngine
from bee2bee_tpu.engine.sampling import sample
from bee2bee_tpu.engine.tokenizer import ByteTokenizer
import jax.numpy as jnp


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(max_seq_len=128, prefill_buckets=(16, 32, 64), dtype="float32", cache_dtype="float32"),
    )


def test_generate_stream_yields_tokens_then_result(engine):
    events = list(engine.generate_stream("hello mesh", max_new_tokens=8))
    # streaming is chunked: each event carries one or more tokens
    streamed = []
    for e in events:
        if "token" in e:
            streamed.extend(e.get("tokens", [e["token"]]))
    assert 0 < len(streamed) <= 8
    done = events[-1]
    assert done["done"] is True
    r = done["result"]
    assert r.new_tokens == len(streamed)
    assert r.token_ids == streamed
    assert r.prompt_tokens > 0
    assert r.ttft_s > 0 and r.latency_s >= r.ttft_s
    assert r.finish_reason in ("length", "eos", "stop")


def test_greedy_is_deterministic(engine):
    a = engine.generate("determinism", max_new_tokens=6)
    b = engine.generate("determinism", max_new_tokens=6)
    assert a.token_ids == b.token_ids


def test_cache_isolation_between_requests(engine):
    """A second request must not see the first request's KV state."""
    base = engine.generate("aaaa", max_new_tokens=5).token_ids
    engine.generate("completely different context", max_new_tokens=5)
    again = engine.generate("aaaa", max_new_tokens=5).token_ids
    assert base == again


def test_long_prompt_left_truncates(engine):
    long_prompt = "x" * 5000
    r = engine.generate(long_prompt, max_new_tokens=16)
    assert r.prompt_tokens <= engine.max_seq_len - 16 - 1
    assert r.new_tokens > 0


def test_max_new_tokens_oversized_is_clamped(engine):
    # serving behavior: an over-budget request clamps to the cache capacity
    # instead of erroring (a default 2048-token request must always work)
    r = engine.generate("hi", max_new_tokens=10_000)
    assert 0 < r.new_tokens < engine.max_seq_len


def test_stop_tokens_halt_generation(engine):
    free = engine.generate("stop test", max_new_tokens=8)
    assert len(free.token_ids) >= 2
    stop_at = free.token_ids[1]
    r = engine.generate("stop test", max_new_tokens=8, stop_tokens=[stop_at])
    assert r.token_ids == free.token_ids[:1]
    assert r.finish_reason == "stop"


def test_metrics_recorded(engine):
    before = engine.metrics.snapshot()["total_requests"]
    engine.generate("metrics", max_new_tokens=4)
    after = engine.metrics.snapshot()
    assert after["total_requests"] == before + 1
    assert after["total_tokens"] > 0


def test_temperature_sampling_varies(engine):
    outs = {
        tuple(engine.generate("sampling seed test", max_new_tokens=8, temperature=1.5).token_ids)
        for _ in range(4)
    }
    assert len(outs) > 1  # rng advances between requests


def test_score_logprobs(engine):
    ids = engine.tokenizer.encode("score me")
    lp = engine.score(ids)
    assert lp.shape == (len(ids) - 1,)
    assert np.all(lp <= 0)


def test_info_schema(engine):
    info = engine.info
    assert info["model"] == "tiny-llama"
    assert info["n_params"] > 0
    assert info["mesh"]["model"] >= 1


# ---- sampling unit behavior -------------------------------------------------


def test_sample_greedy_argmax():
    logits = jnp.asarray([[0.1, 2.0, -1.0, 0.5]])
    assert int(sample(logits, jax.random.key(0), temperature=0.0)[0]) == 1


def test_sample_topk_restricts_support():
    logits = jnp.asarray([[5.0, 4.0, -10.0, -10.0]])
    toks = {
        int(sample(logits, jax.random.key(s), temperature=1.0, top_k=2)[0])
        for s in range(50)
    }
    assert toks <= {0, 1}


def test_sample_topp_keeps_nucleus():
    # one dominant token (p>0.99): top_p=0.5 must always pick it
    logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0]])
    toks = {
        int(sample(logits, jax.random.key(s), temperature=1.0, top_p=0.5)[0])
        for s in range(20)
    }
    assert toks == {0}


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer(50257)
    text = "hello wörld — bee2bee"
    assert tok.decode(tok.encode(text)) == text
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert all(0 <= i < tok.vocab_size for i in ids)


def test_top_p_zero_degrades_to_greedy():
    logits = jnp.asarray([[0.1, 2.0, -1.0, 0.5]])
    for s in range(10):
        assert int(sample(logits, jax.random.key(s), temperature=1.0, top_p=0.0)[0]) == 1


def test_random_init_finite_at_depth():
    # fan-in must come from the true input dim, not the stacked layer dim:
    # a deep-ish random model must produce finite logits
    from bee2bee_tpu.models import core, get_config
    from dataclasses import replace
    cfg = replace(get_config("tiny-llama"), n_layers=16)
    params = core.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    logits, _ = core.forward(params, cfg, jnp.ones((1, 8), jnp.int32), None, 0)
    assert bool(jnp.isfinite(logits).all())


def test_real_model_names_never_resolve_to_tiny_configs():
    from bee2bee_tpu.models import get_config
    assert get_config("openai-community/gpt2").name == "gpt2"
    assert get_config("gpt2").name == "gpt2"
    assert get_config("tiny-gpt2").name == "tiny-gpt2"


def test_max_new_tokens_zero_streams_nothing(engine):
    evs = list(engine.generate_stream("hi", max_new_tokens=0))
    assert len(evs) == 1 and evs[0]["done"]
    assert evs[0]["result"].new_tokens == 0
    assert engine.generate("hi", max_new_tokens=0).new_tokens == 0


def test_engine_on_data_axis_mesh_does_not_crash():
    from bee2bee_tpu.parallel import MeshSpec, build_mesh
    mesh = build_mesh(MeshSpec(data=2, model=2))
    eng = InferenceEngine(
        "tiny-llama", mesh=mesh,
        engine_config=EngineConfig(max_seq_len=64, prefill_buckets=(16,), dtype="float32", cache_dtype="float32"),
    )
    assert eng.generate("data axis", max_new_tokens=4).new_tokens > 0


class TestAutoAttention:
    """attention='auto' resolves at engine build: flash on a supporting TPU
    layout, dense everywhere else (EngineConfig.attention docstring)."""

    def test_auto_resolves_to_dense_on_cpu(self):
        eng = InferenceEngine(
            "tiny-llama",
            engine_config=EngineConfig(
                max_seq_len=64, dtype="float32", cache_dtype="float32",
                attention="auto",
            ),
        )
        assert eng.engine_cfg.attention == "dense"
        # and the engine actually works after resolution
        r = eng.generate([1, 2, 3], max_new_tokens=4, temperature=0.0)
        assert r.new_tokens == 4
        eng.close()

    @staticmethod
    def _fake_tpu_mesh(shape=None):
        """A mesh stand-in whose devices report platform='tpu'. Resolution
        reads the MESH's devices (not jax.devices()): an explicit CPU mesh
        on a TPU-default host must resolve to dense, so the platform
        source of truth is the mesh itself."""
        import types

        dev = types.SimpleNamespace(platform="tpu")
        return types.SimpleNamespace(
            devices=np.array([dev]), shape=dict(shape or {})
        )

    def test_auto_resolves_to_flash_on_tpu_mesh(self):
        # resolution must consult the real layout validator: tiny-llama's
        # 4 heads on a 1-device mesh pass it
        eng = InferenceEngine.__new__(InferenceEngine)
        from bee2bee_tpu.models.config import get_config

        eng.model_cfg = get_config("tiny-llama")
        eng.engine_cfg = EngineConfig(attention="auto")
        eng.mesh = self._fake_tpu_mesh()
        assert eng._resolve_auto_attention() == "flash"

    def test_auto_falls_back_to_dense_on_unsupported_layout(self):
        from bee2bee_tpu.models.config import get_config

        eng = InferenceEngine.__new__(InferenceEngine)
        # tiny-llama has n_kv_heads=2 (GQA): replicated KV over model=4
        # is the layout validate_flash_mesh rejects
        eng.model_cfg = get_config("tiny-llama")
        eng.engine_cfg = EngineConfig(attention="auto")
        eng.mesh = self._fake_tpu_mesh(shape={"model": 4})
        assert eng._resolve_auto_attention() == "dense"

    def test_auto_ignores_default_backend_when_mesh_is_cpu(self, monkeypatch):
        # TPU-default host, explicit CPU mesh: flash would run the pallas
        # kernel in interpret mode — auto must pick dense
        import types

        from bee2bee_tpu.models.config import get_config
        from bee2bee_tpu.parallel.mesh import local_mesh

        cpu_mesh = local_mesh()  # real CPU devices, built pre-monkeypatch
        monkeypatch.setattr(
            jax, "devices",
            lambda *a, **k: [types.SimpleNamespace(platform="tpu")],
        )
        eng = InferenceEngine.__new__(InferenceEngine)
        eng.model_cfg = get_config("tiny-llama")
        eng.engine_cfg = EngineConfig(attention="auto")
        eng.mesh = cpu_mesh
        assert eng._resolve_auto_attention() == "dense"

    def test_auto_does_not_mutate_callers_config(self):
        shared = EngineConfig(
            max_seq_len=64, dtype="float32", cache_dtype="float32",
            attention="auto",
        )
        eng = InferenceEngine("tiny-llama", engine_config=shared)
        assert shared.attention == "auto"  # caller's object untouched
        assert eng.engine_cfg.attention in ("dense", "flash")
        eng.close()

    def test_auto_resolves_to_sp_on_seq_mesh(self):
        # a seq axis exists only for sequence-parallel cache sharding;
        # flash/dense would silently replicate the cache across it
        from bee2bee_tpu.models.config import get_config

        eng = InferenceEngine.__new__(InferenceEngine)
        eng.model_cfg = get_config("tiny-llama")
        eng.engine_cfg = EngineConfig(attention="auto")
        eng.mesh = self._fake_tpu_mesh(shape={"seq": 4, "model": 1})
        assert eng._resolve_auto_attention() == "sp"


@pytest.mark.parametrize("family", ["tiny-phi", "tiny-neox", "tiny-gptj", "tiny-falcon"])
def test_parallel_block_families_serve(family):
    """parallel-block families (phi: shared norm; neox: dual norm +
    interleaved-QKV heritage) through the cached decode path: prefill
    positions and per-row decode offsets must agree with the no-cache
    forward (greedy continuation check)."""
    eng = InferenceEngine(
        family,
        engine_config=EngineConfig(
            max_seq_len=64, prefill_buckets=(16,), dtype="float32",
            cache_dtype="float32",
        ),
    )
    r = eng.generate([1, 7, 42, 9], max_new_tokens=6, temperature=0.0)
    assert r.new_tokens == 6
    # cached decode == full forward: replay prompt+output through the
    # no-cache forward and check each generated token was the argmax
    from bee2bee_tpu.models import core
    full = [1, 7, 42, 9] + r.token_ids
    logits, _ = core.forward(
        eng.params, eng.model_cfg, jnp.asarray([full], jnp.int32), None,
        jnp.int32(0),
    )
    preds = np.asarray(jnp.argmax(logits[0, 3:-1], axis=-1))
    np.testing.assert_array_equal(preds, np.asarray(r.token_ids))
    eng.close()
