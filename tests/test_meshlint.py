"""meshlint (bee2bee_tpu/analysis): the tier-1 ratchet gate + pass self-tests.

The gate test runs the analyzer over the installed package: any finding not
grandfathered by analysis/baseline.json fails tier-1 — that is the ratchet.
The self-tests prove each pass family actually catches its bug class on
small known-bad fixtures (so a silently-broken pass can't hide behind a
clean repo), and that seeding a typo'd sampling key into a real frame
literal is caught.
"""

from __future__ import annotations

from pathlib import Path

from bee2bee_tpu import protocol
from bee2bee_tpu.analysis import (
    analyze_paths,
    analyze_source,
    declared_key_universe,
    filter_baselined,
    load_baseline,
    rule_catalog,
)
from bee2bee_tpu.analysis.core import PACKAGE_ROOT
from bee2bee_tpu.analysis.schema import FRAME_SCHEMAS, TASK_SCHEMAS


def _rules(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------------ the gate


def test_package_is_clean_under_baseline():
    """THE tier-1 gate: no non-baselined finding anywhere in the package."""
    findings = analyze_paths([PACKAGE_ROOT])
    new, _old = filter_baselined(findings, load_baseline())
    assert not new, "new meshlint findings (fix them or, for deliberate " \
        "violations, add `# meshlint: ignore[rule] -- reason`):\n" + \
        "\n".join(f.render() for f in new)


def test_seeded_sampling_key_typo_is_caught():
    """The acceptance scenario: typo a sampling key in a REAL frame literal
    (node.py's gen_request) and the frames pass must flag it."""
    src = (PACKAGE_ROOT / "meshnet" / "node.py").read_text()
    seeded = src.replace("temperature=temperature,", "temperture=temperature,", 1)
    assert seeded != src, "node.py gen_request literal moved; update the seed"
    findings = analyze_source(seeded, "meshnet/node.py")
    assert any(
        f.rule == "ML-F001" and "temperture" in f.message for f in findings
    ), findings


def test_seeded_task_field_typo_is_caught():
    src = (PACKAGE_ROOT / "meshnet" / "pipeline.py").read_text()
    seeded = src.replace('"rng_seed": self.rng_seed,', '"rngseed": self.rng_seed,', 1)
    assert seeded != src
    assert any(
        f.rule == "ML-F001" and "rngseed" in f.message
        for f in analyze_source(seeded, "meshnet/pipeline.py")
    )


def test_seeded_message_read_typo_is_caught():
    src = (PACKAGE_ROOT / "meshnet" / "node.py").read_text()
    seeded = src.replace('data.get("peer_id")', 'data.get("peerid")', 1)
    assert seeded != src
    assert any(
        f.rule == "ML-F003" and "peerid" in f.message
        for f in analyze_source(seeded, "meshnet/node.py")
    )


# ------------------------------------------------------- frames pass fixtures


def test_frames_pass_known_bad_fixture():
    src = '''
from .. import protocol

async def send(ws, rid):
    await ws.send(protocol.encode(
        protocol.msg(protocol.GEN_REQUEST, rid=rid, prompt="x", top_kk=5)))
    await ws.send(protocol.encode({"type": protocol.GEN_CHUNK, "rid": rid}))

async def _handle_gen_request(ws, data):
    return data.get("promt")
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-F001" in rules  # top_kk undeclared
    assert "ML-F002" in rules  # gen_chunk without text
    assert "ML-F003" in rules  # read of "promt"
    assert "ML-F004" in rules  # no sampling forwarding on that gen_request


def test_frames_pass_run_stage_task_fields():
    src = '''
from .. import protocol

async def load(self, peer):
    await self.node.run_stage_task(
        peer, protocol.TASK_PART_LOAD,
        {"model": "m", "n_stages": 2, "staeg": 0},
    )
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-F001" in rules  # staeg
    assert "ML-F002" in rules  # stage missing


def test_frames_pass_accepts_clean_constructions():
    src = '''
from .. import protocol

async def send(ws, rid, extra):
    await ws.send(protocol.encode(protocol.msg(
        protocol.GEN_REQUEST, rid=rid, prompt="x", top_k=4, stop=["a"])))
    await ws.send(protocol.encode(protocol.msg(
        protocol.GEN_SUCCESS, rid=rid, **extra)))

async def _handle_gen_request(ws, data):
    return data.get("prompt"), data.get("top_p"), data["_tensors"]
'''
    assert analyze_source(src, "meshnet/fixture.py") == []


def test_frames_pass_out_of_scope_paths_unchecked():
    src = 'x = {"type": "gen_chunk"}\n'  # missing text+id: finding in scope
    assert _rules(analyze_source(src, "web/fixture.py")).count("ML-F002") == 2
    assert analyze_source(src, "engine/fixture.py") == []


def test_frames_pass_fleet_frames_declared_and_checked():
    """ISSUE 13 CI satellite: the fleet control frames are registry-
    declared, the fleet/ package is in the frames-pass scope, and the
    known-bad fixture proves each bug class is caught there."""
    for op in (protocol.FLEET_LEASE, protocol.FLEET_ACTION, protocol.FLEET_ACK):
        assert op in FRAME_SCHEMAS, f"{op} missing from the schema registry"
    assert "holder" in FRAME_SCHEMAS[protocol.FLEET_LEASE].required
    assert "epoch" in FRAME_SCHEMAS[protocol.FLEET_ACTION].required
    src = '''
from .. import protocol

async def announce(node, ws, rid):
    await ws.send(protocol.encode(protocol.msg(
        protocol.FLEET_LEASE, holder=node.peer_id, epoch=1, ttl=30.0)))
    await ws.send(protocol.encode(protocol.msg(
        protocol.FLEET_ACTION, rid=rid, action="drain", epoch=2)))

async def _handle_fleet_ack(ws, data):
    return data.get("okk")
'''
    rules = _rules(analyze_source(src, "fleet/fixture.py"))
    assert "ML-F001" in rules  # `ttl` is not a declared lease key (ttl_s is)
    assert "ML-F002" in rules  # lease missing ttl_s / action missing holder
    assert "ML-F003" in rules  # read of undeclared "okk"
    # the same constructions built right are clean
    good = '''
from .. import protocol

async def announce(node, ws, rid):
    await ws.send(protocol.encode(protocol.msg(
        protocol.FLEET_LEASE, holder=node.peer_id, epoch=1, ttl_s=30.0)))
    await ws.send(protocol.encode(protocol.msg(
        protocol.FLEET_ACTION, rid=rid, action="drain", epoch=2,
        holder=node.peer_id)))

async def _handle_fleet_ack(ws, data):
    return data.get("ok")
'''
    assert analyze_source(good, "fleet/fixture.py") == []


def test_frames_pass_adapter_frames_declared_and_checked():
    """ISSUE 14 CI satellite: the multi-adapter serving keys are registry-
    declared — `adapter` on GEN_REQUEST and the ADAPTER_ANNOUNCE frame —
    and the known-bad fixtures prove each bug class is caught (a typo'd
    adapter key is a silently-ignored tenant selection on old peers)."""
    assert protocol.ADAPTER_ANNOUNCE in FRAME_SCHEMAS
    assert protocol.ADAPTER in FRAME_SCHEMAS[protocol.GEN_REQUEST].optional
    assert "adapters" in FRAME_SCHEMAS[protocol.ADAPTER_ANNOUNCE].required
    src = '''
from .. import protocol

async def announce(node, ws, rid):
    await ws.send(protocol.encode(protocol.msg(
        protocol.ADAPTER_ANNOUNCE, peer_id=node.peer_id, service="tpu")))
    await ws.send(protocol.encode(protocol.msg(
        protocol.GEN_REQUEST, rid=rid, prompt="x", top_k=2, stop=["a"],
        adaptr="acme")))

async def _handle_adapter_announce(ws, data):
    return data.get("adaptrs")
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-F001" in rules  # `adaptr` undeclared on gen_request
    assert "ML-F002" in rules  # announce missing its `adapters` list
    assert "ML-F003" in rules  # read of undeclared "adaptrs"
    good = '''
from .. import protocol

async def announce(node, ws, rid):
    await ws.send(protocol.encode(protocol.msg(
        protocol.ADAPTER_ANNOUNCE, peer_id=node.peer_id, service="tpu",
        adapters=["acme"], models=["m", "m:acme"])))
    await ws.send(protocol.encode(protocol.msg(
        protocol.GEN_REQUEST, rid=rid, prompt="x", top_k=2, stop=["a"],
        adapter="acme")))

async def _handle_adapter_announce(ws, data):
    return data.get("adapters"), data.get("models")
'''
    assert analyze_source(good, "meshnet/fixture.py") == []


def test_frames_pass_draft_frames_declared_and_checked():
    """ISSUE 19 CI satellite: the mesh-drafting wire protocol is registry-
    declared — draft_request/draft_result (meshnet/draft.py) — and the
    known-bad fixture proves each bug class is caught (a typo'd draft key
    is a silently-empty draft stream: the target decodes plain forever
    while the draft peer burns compute into dropped frames)."""
    assert protocol.DRAFT_REQUEST in FRAME_SCHEMAS
    assert protocol.DRAFT_RESULT in FRAME_SCHEMAS
    assert "rid" in FRAME_SCHEMAS[protocol.DRAFT_REQUEST].required
    assert "tokens" in FRAME_SCHEMAS[protocol.DRAFT_REQUEST].optional
    assert "rid" in FRAME_SCHEMAS[protocol.DRAFT_RESULT].required
    assert "pos" in FRAME_SCHEMAS[protocol.DRAFT_RESULT].optional
    src = '''
from .. import protocol

async def request_draft(node, ws, rid, ctx):
    await ws.send(protocol.encode(protocol.msg(
        protocol.DRAFT_REQUEST, rid=rid, base=0, tokns=ctx, k=6)))

async def answer_draft(node, ws, draft):
    await ws.send(protocol.encode(protocol.msg(
        protocol.DRAFT_RESULT, pos=3, draft=draft)))

async def _handle_draft_result(ws, data):
    return data.get("drft")
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-F001" in rules  # `tokns` undeclared on draft_request
    assert "ML-F002" in rules  # draft_result missing its required `rid`
    assert "ML-F003" in rules  # read of undeclared "drft"
    good = '''
from .. import protocol

async def request_draft(node, ws, rid, ctx):
    await ws.send(protocol.encode(protocol.msg(
        protocol.DRAFT_REQUEST, rid=rid, base=0, tokens=ctx, k=6,
        model="tiny-llama")))

async def answer_draft(node, ws, rid, draft):
    await ws.send(protocol.encode(protocol.msg(
        protocol.DRAFT_RESULT, rid=rid, pos=3, draft=draft)))

async def _handle_draft_result(ws, data):
    return data.get("pos"), data.get("draft"), data.get("reprime")
'''
    assert analyze_source(good, "meshnet/fixture.py") == []


def test_seeded_draft_frame_typos_are_caught():
    """Typo the draft protocol in the REAL sources and meshlint must
    object: a misspelled construct key on the server's draft_result
    (meshnet/draft.py) and a misspelled read in the node's draft_request
    handler (meshnet/node.py)."""
    src = (PACKAGE_ROOT / "meshnet" / "draft.py").read_text()
    seeded = src.replace(
        "protocol.DRAFT_RESULT, rid=rid, pos=pos,",
        "protocol.DRAFT_RESULT, rid=rid, poss=pos,", 1,
    )
    assert seeded != src, "draft.py result literal moved; update the seed"
    assert "ML-F001" in _rules(analyze_source(seeded, "meshnet/draft.py"))

    src = (PACKAGE_ROOT / "meshnet" / "node.py").read_text()
    seeded = src.replace(
        'rid=str(data.get("rid") or ""), error="no_drafter",',
        'rid=str(data.get("ird") or ""), error="no_drafter",', 1,
    )
    assert seeded != src, "node.py draft handler moved; update the seed"
    assert any(
        f.rule == "ML-F003" and "ird" in f.message
        for f in analyze_source(seeded, "meshnet/node.py")
    )


# -------------------------------------------------------- async pass fixtures


def test_async_pass_known_bad_fixture():
    src = '''
import time, requests

async def bad(self, ws):
    time.sleep(1)
    requests.post("http://x", json={})
    async with self._lock:
        await ws.send("hi")
    await ws.recv()
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert rules.count("ML-A001") == 2
    assert "ML-A003" in rules
    assert "ML-A002" in rules


def test_async_pass_clean_patterns_pass():
    src = '''
import asyncio
import websockets

async def good(self, addr):
    async with self._lock:
        targets = list(self.peers)
    ws = await websockets.connect(addr, open_timeout=10)
    await self.clock.sleep(0.1)  # the clock seam — also ML-C001-clean

    def offloaded():
        import time
        time.sleep(1)  # meshlint: ignore[ML-C001] -- real wall wait in an executor thread

    await asyncio.get_running_loop().run_in_executor(None, offloaded)
'''
    assert analyze_source(src, "meshnet/fixture.py") == []


def test_async_pass_ws_connect_without_timeout():
    src = '''
import websockets

async def dial(addr):
    return await websockets.connect(addr)
'''
    assert "ML-A002" in _rules(analyze_source(src, "meshnet/fixture.py"))
    # outside the meshnet/web hot-path scope the timeout rule stays quiet
    assert analyze_source(src, "services/fixture.py") == []


# ---------------------------------------------------------- jax pass fixtures


def test_jax_pass_known_bad_fixture():
    src = '''
import jax
import jax.numpy as jnp
import numpy as np

def _decode_fn(cache, x, k):
    v = x.item()
    h = np.asarray(x)
    n = int(k)
    if jnp.any(x > 0):
        x = x + 1
    return x

decode = jax.jit(_decode_fn)
'''
    rules = _rules(analyze_source(src, "engine/fixture.py"))
    assert rules.count("ML-J001") == 3
    assert "ML-J002" in rules


def test_jax_pass_only_flags_jit_reachable():
    src = '''
import numpy as np

def host_side(x):
    return np.asarray(x).item()  # never jit-compiled: fine
'''
    assert analyze_source(src, "engine/fixture.py") == []


def test_jax_pass_sees_spec_verify_wiring():
    """The engine's speculative-decode verify root is wired as
    ``self._spec_verify = jax.jit(self._spec_verify_fn, ...)`` — the
    method-attribute form of jit wrapping. Pin that the root collector
    resolves it: a host sync or traced branch seeded into a fixture
    with exactly that wiring must be flagged (a collector regression
    would silently stop scanning the hottest new jit root)."""
    src = '''
import jax
import jax.numpy as jnp

class Engine:
    def __init__(self):
        self._spec_verify = jax.jit(self._spec_verify_fn, donate_argnums=(4,))

    def _spec_verify_fn(self, params, cur, drafts, draft_lens, cache,
                        offsets, key):
        n = int(draft_lens)
        if jnp.any(cur > 0):
            cur = cur + 1
        return cur, cache
'''
    rules = _rules(analyze_source(src, "engine/engine.py"))
    assert "ML-J001" in rules and "ML-J002" in rules


def test_jax_pass_covers_spec_module_and_real_verify_is_clean():
    """engine/spec.py is inside the jax-pass scope (a path move out of
    engine/ would silently drop it from scanning), and the REAL spec
    module + engine (with the verify fn) lint clean — the ratchet
    baseline stays empty."""
    from bee2bee_tpu.analysis.jaxhygiene import JaxHygienePass

    assert JaxHygienePass().applies("engine/spec.py")
    spec_py = PACKAGE_ROOT / "engine" / "spec.py"
    engine_py = PACKAGE_ROOT / "engine" / "engine.py"
    assert "_spec_verify_fn" in engine_py.read_text()  # the root exists
    assert analyze_paths([spec_py, engine_py]) == []


def test_jax_pass_sees_pallas_call_kernel_roots():
    """ops/ragged.py wires its kernel as
    ``pl.pallas_call(functools.partial(_kernel, ...), ...)`` — pin that
    the root collector resolves the pallas_call body through the partial:
    a host sync or traced-value branch seeded into a fixture with exactly
    that wiring must be flagged (a collector regression would silently
    stop scanning the engine's hottest kernel), and the REAL ragged +
    flash kernel modules lint clean so the ratchet baseline stays empty."""
    src = '''
import functools
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(tables_ref, q_ref, o_ref, *, scale):
    n = int(scale)
    v = q_ref[0].item()
    if jnp.any(q_ref[0] > 0):
        v = v + 1
    o_ref[0] = v


def wrapper(q, tables):
    kernel = functools.partial(_kernel, scale=2.0)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(tables, q)
'''
    rules = _rules(analyze_source(src, "ops/fixture.py"))
    assert "ML-J001" in rules and "ML-J002" in rules
    from bee2bee_tpu.analysis.jaxhygiene import JaxHygienePass

    assert JaxHygienePass().applies("ops/ragged.py")
    ragged_py = PACKAGE_ROOT / "ops" / "ragged.py"
    flash_py = PACKAGE_ROOT / "ops" / "flash.py"
    assert "pallas_call" in ragged_py.read_text()  # the root exists
    assert analyze_paths([ragged_py, flash_py]) == []


def test_jax_pass_catches_host_sync_in_quantize_on_write_root():
    """ISSUE 12: the int8 KV pool's quantize-on-write runs inside the
    engine's jit roots (prefill/decode/spec-verify) and its scan-carried
    layer body — a host-side ``.item()`` / numpy cast there would put a
    device→host sync on EVERY cache write. Pin that the pass catches
    exactly that wiring on a known-bad fixture (jit-root method + scan
    body, mirroring engine._prefill_fn → core.forward's layer scan), and
    that the REAL modules owning the quantized pool lint clean so the
    ratchet baseline stays EMPTY."""
    src = '''
import jax
import jax.numpy as jnp
import numpy as np


class Engine:
    def __init__(self):
        self._prefill = jax.jit(self._prefill_fn, donate_argnums=(2,))

    def _prefill_fn(self, params, tokens, cache, blk, slot):
        # quantize-on-write gone wrong: host amax + scalar cast per write
        amax = np.asarray(tokens).max()
        n = int(slot)
        scale = cache["k_scale"].item()
        return cache, tokens


def forward(pool, scale, xT):
    def layer(carry, xs):
        pool, scale = carry
        if jnp.any(scale > 0):
            pool = pool
        q = np.asarray(xT)
        return (pool, scale), None
    return jax.lax.scan(layer, (pool, scale), xT)
'''
    rules = _rules(analyze_source(src, "engine/engine.py"))
    assert "ML-J001" in rules and "ML-J002" in rules
    from bee2bee_tpu.analysis.jaxhygiene import JaxHygienePass

    assert JaxHygienePass().applies("models/core.py")
    core_py = PACKAGE_ROOT / "models" / "core.py"
    ragged_py = PACKAGE_ROOT / "ops" / "ragged.py"
    scheduler_py = PACKAGE_ROOT / "engine" / "scheduler.py"
    assert "_quantized_page_write" in core_py.read_text()  # the root exists
    assert analyze_paths([core_py, ragged_py, scheduler_py]) == []


def test_jax_pass_sees_decorators_and_scan_bodies():
    src = '''
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return x.item()

def outer(xs):
    def step(carry, x):
        if jnp.sum(x):
            carry = carry + 1
        return carry, x
    return jax.lax.scan(step, 0, xs)
'''
    rules = _rules(analyze_source(src, "models/fixture.py"))
    assert "ML-J001" in rules and "ML-J002" in rules


# ------------------------------------------------- suppressions and baseline


def test_suppression_requires_reason():
    src = '''
async def f(ws):
    await ws.recv()  # meshlint: ignore[ML-A002]
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-S001" in rules and "ML-A002" in rules  # unexplained ≠ suppressed


def test_suppression_with_reason_suppresses_only_that_rule():
    src = '''
async def f(ws):
    await ws.recv()  # meshlint: ignore[ML-A002] -- loopback shim, in-process peer
'''
    assert analyze_source(src, "meshnet/fixture.py") == []
    wildcard = src.replace("[ML-A002]", "[*]")
    assert analyze_source(wildcard, "meshnet/fixture.py") == []
    wrong_rule = src.replace("[ML-A002]", "[ML-A001]")
    assert _rules(analyze_source(wrong_rule, "meshnet/fixture.py")) == ["ML-A002"]


def test_baseline_is_a_consuming_multiset():
    src = '''
async def f(ws):
    await ws.recv()

async def g(ws):
    await ws.recv()
'''
    findings = analyze_source(src, "meshnet/fixture.py")
    assert _rules(findings) == ["ML-A002", "ML-A002"]
    # identical snippets: one baseline entry absorbs exactly one finding
    from collections import Counter
    baseline = Counter([findings[0].key()])
    new, old = filter_baselined(findings, baseline)
    assert len(new) == 1 and len(old) == 1


def test_cli_exit_codes(tmp_path):
    from bee2bee_tpu.analysis.__main__ import main

    bad = tmp_path / "meshnet"
    bad.mkdir()
    (bad / "x.py").write_text(
        "import time\n\nasync def f(ws):\n    time.sleep(1)\n"
    )
    # a file outside the package scopes by basename; the blocking-call
    # rule applies to every path, so the CLI must exit 1 on it
    assert main([str(bad), "--no-baseline"]) != 0
    assert main([str(PACKAGE_ROOT / "protocol.py")]) == 0
    assert main(["--list-rules"]) == 0


# -------------------------------------------------------- registry invariants


def test_every_message_type_has_a_schema():
    assert set(FRAME_SCHEMAS) >= set(protocol.MESSAGE_TYPES)


def test_every_task_kind_constant_has_a_schema():
    kinds = {
        v
        for k, v in vars(protocol).items()
        if k.startswith("TASK_") and isinstance(v, str) and v != protocol.TASK_ERROR
    }
    assert kinds <= set(TASK_SCHEMAS)


def test_sampling_keys_are_in_the_declared_universe():
    assert set(protocol.SAMPLING_KEYS) <= declared_key_universe()


def test_tenant_and_admission_keys_are_declared():
    """ISSUE 7: the tenant identity field rides gen_request, and every
    admission rejection (the typed 429/503 contract over p2p) carries
    error_kind + retry_after_s on GEN_ERROR — pinned here so a protocol
    change can't drop them from the registry silently."""
    assert protocol.TENANT in FRAME_SCHEMAS[protocol.GEN_REQUEST].allowed_keys()
    gen_error = FRAME_SCHEMAS[protocol.GEN_ERROR]
    assert {"error_kind", "retry_after_s"} <= gen_error.allowed_keys()
    assert {protocol.TENANT, "error_kind", "retry_after_s"} <= declared_key_universe()


def test_admission_rejection_fixture_pins_typed_fields():
    """A GEN_ERROR admission rejection with a typo'd retry field (the
    header-style `retry_after` instead of the wire's `retry_after_s`) is
    exactly the silently-dropped-key class meshlint exists for; the
    correctly-typed construction passes clean."""
    bad = '''
from .. import protocol

async def reject(ws, rid, rej):
    await ws.send(protocol.encode(protocol.msg(
        protocol.GEN_ERROR, rid=rid, error="admission_rejected: rate",
        error_kind="rate_limited", retry_after=1.0)))
'''
    rules = _rules(analyze_source(bad, "meshnet/fixture.py"))
    assert "ML-F001" in rules, rules
    good = bad.replace("retry_after=1.0", "retry_after_s=1.0")
    assert analyze_source(good, "meshnet/fixture.py") == []


def test_seeded_admission_rejection_typo_is_caught_in_real_node():
    """Seed the retry_after_s typo into node.py's REAL admission-reject
    frame literal: the frames pass must flag it (proves the real
    construction is statically checked, not spread-exempted)."""
    src = (PACKAGE_ROOT / "meshnet" / "node.py").read_text()
    seeded = src.replace(
        "retry_after_s=rej.retry_after_s,", "retry_after=rej.retry_after_s,", 1
    )
    assert seeded != src, "node.py admission-reject literal moved; update the seed"
    assert any(
        f.rule == "ML-F001" and "retry_after" in f.message
        for f in analyze_source(seeded, "meshnet/node.py")
    )


def test_rule_catalog_covers_all_emitted_rules():
    cat = rule_catalog()
    for rule in ("ML-F001", "ML-F002", "ML-F003", "ML-F004",
                 "ML-A001", "ML-A002", "ML-A003",
                 "ML-J001", "ML-J002", "ML-S001"):
        assert rule in cat


def test_out_of_tree_paths_scope_by_package_structure(tmp_path):
    """Analyzing a checkout/copy OUTSIDE the installed package must still
    scope files by their meshnet/engine/... structure — a basename
    fallback would silently skip the frames/jax passes there."""
    from bee2bee_tpu.analysis.core import virtual_path

    d = tmp_path / "clone" / "bee2bee_tpu" / "meshnet"
    d.mkdir(parents=True)
    f = d / "node.py"
    f.write_text("")
    assert virtual_path(f) == "meshnet/node.py"
    d2 = tmp_path / "copy" / "engine"
    d2.mkdir(parents=True)
    assert virtual_path(d2 / "scheduler.py") == "engine/scheduler.py"


def test_f004_attributed_per_frame_not_per_function():
    """One copy_sampling call must exempt ONLY the frame it targets —
    a second knob-less gen_request in the same function still fails."""
    src = '''
from .. import protocol

async def two_frames(ws, payload, rid):
    covered = {"type": protocol.GEN_REQUEST, "rid": rid, "prompt": "x"}
    protocol.copy_sampling(payload, covered)
    await ws.send(protocol.encode(covered))
    naked = {"type": protocol.GEN_REQUEST, "rid": rid, "prompt": "y"}
    await ws.send(protocol.encode(naked))
'''
    findings = analyze_source(src, "web/fixture.py")
    f004 = [f for f in findings if f.rule == "ML-F004"]
    assert len(f004) == 1 and "naked" in f004[0].snippet, findings


def test_f004_covers_msg_assigned_frames():
    src = '''
from .. import protocol

async def send(ws, body, rid):
    m = protocol.msg(protocol.GEN_REQUEST, rid=rid, prompt="x")
    protocol.copy_sampling(body, m)
    await ws.send(protocol.encode(m))
'''
    assert analyze_source(src, "meshnet/fixture.py") == []


def test_a003_lock_naming_does_not_match_block_vocabulary():
    """'block' contains the substring 'lock': the paged-cache vocabulary
    (block pools, blocked peers) must not trip the lock-held rule."""
    src = '''
async def fine(self, ws):
    async with self.block_pool_guard:
        await ws.send("hi")
    async with self.unblock_gate:
        await ws.send("hi")

async def held(self, ws):
    async with self.rw_lock:
        await ws.send("hi")
'''
    findings = analyze_source(src, "meshnet/fixture.py")
    assert _rules(findings) == ["ML-A003"]
    # the one finding anchors to the await inside the real lock block
    assert findings[0].line == 10


# --------------------------------------------------- telemetry pass fixtures


def test_telemetry_pass_known_bad_fixture():
    """ML-T001: every dynamic-name construction a span/metric call can
    smuggle a request-varying string through — f-string, + concat,
    %-format, .format()."""
    src = '''
from ..tracing import get_tracer, annotate
from ..metrics import get_registry

def f(rid, op):
    with get_tracer().span(f"gen.{rid}"):
        pass
    with annotate("stage." + op):
        pass
    get_registry().counter("frames_%s" % op).inc()
    get_registry().histogram(name="lat.{}".format(op)).observe(1.0)
'''
    rules = _rules(analyze_source(src, "engine/fixture.py"))
    assert rules == ["ML-T001"] * 4, rules


def test_telemetry_pass_accepts_literal_and_variable_names():
    """Literal dotted constants pass; so does forwarding a plain variable
    (the literal is checked at ITS call site), and request-varying data in
    attrs/labels — the pattern the rule exists to steer people toward."""
    src = '''
from ..tracing import get_tracer
from ..metrics import get_registry

SPAN_NAME = "gen.local"

def f(rid, op):
    with get_tracer().span("gen.p2p", rid=rid):
        pass
    with get_tracer().span(SPAN_NAME):
        pass
    get_registry().counter("mesh.frames_sent").inc(op=op)
    "a,b".split(",")[0].count("a")  # str.count is not Tracer.count
'''
    assert analyze_source(src, "meshnet/fixture.py") == []


def test_telemetry_pass_scans_whole_package():
    """Telemetry calls live in engine/, meshnet/, services/, web/ and
    api.py alike — the pass must not scope itself out of any of them."""
    from bee2bee_tpu.analysis.telemetry import TelemetryPass

    p = TelemetryPass()
    for path in ("engine/scheduler.py", "meshnet/node.py", "api.py",
                 "web/gateway.py", "services/base.py", "tracing.py"):
        assert p.applies(path), path


def test_telemetry_rule_in_catalog():
    assert "ML-T001" in rule_catalog()


# --------------------------------------------------- clock-seam pass fixtures


def test_clock_pass_known_bad_fixture():
    """ML-C001: every direct wall-clock read and bare asyncio timer in a
    clock-seamed package is a finding — each one silently re-couples a
    code path to the host clock and breaks deterministic simulation."""
    src = '''
import asyncio
import time

async def tick(self):
    start = time.time()
    mono = time.monotonic()
    perf = time.perf_counter()
    await asyncio.sleep(1.0)
    await asyncio.wait_for(self.q.get(), timeout=2.0)
    time.sleep(0.1)
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert rules.count("ML-C001") == 6, rules


def test_clock_pass_seam_calls_are_clean():
    """The seam itself — clock.time()/sleep()/wait_for(), however the
    clock is reached — never matches the bare-module names."""
    src = '''
from ..clock import get_clock

async def tick(self):
    now = self.clock.time()
    await self.clock.sleep(1.0)
    await self.clock.wait_for(self.q.get(), timeout=2.0)
    mono = get_clock().monotonic()
'''
    assert analyze_source(src, "meshnet/fixture.py") == []


def test_clock_pass_scope_covers_all_seamed_packages():
    from bee2bee_tpu.analysis.clockseam import ClockSeamPass

    p = ClockSeamPass()
    for path in ("meshnet/node.py", "fleet/controller.py",
                 "router/policy.py", "health.py"):
        assert p.applies(path), path
    # unseamed packages keep their wall clocks without findings
    for path in ("engine/scheduler.py", "services/base.py", "bench.py",
                 "simnet/clock.py"):
        assert not p.applies(path), path
    src = "import time\n\ndef f():\n    return time.time()\n"
    assert analyze_source(src, "engine/fixture.py") == []


def test_clock_pass_suppression_and_real_exemptions():
    """A justified same-line ignore suppresses the finding; the shipped
    exemptions (NAT round trips in runtime.py, thread joins in
    health.py) carry one, so the ratchet baseline stays EMPTY."""
    src = '''
import time

def deadline(timeout_s):
    return time.time() + timeout_s  # meshlint: ignore[ML-C001] -- real thread-join deadline
'''
    assert analyze_source(src, "health.py") == []
    runtime_py = PACKAGE_ROOT / "meshnet" / "runtime.py"
    health_py = PACKAGE_ROOT / "health.py"
    assert "ignore[ML-C001]" in runtime_py.read_text()
    assert "ignore[ML-C001]" in health_py.read_text()
    assert analyze_paths([runtime_py, health_py]) == []


def test_clock_rule_in_catalog():
    assert "ML-C001" in rule_catalog()


# ---------------------------------------------------- raceguard pass fixtures


def test_raceguard_r001_known_bad_fixture():
    """ML-R001: check `self.X`, await, then write `self.X` without
    re-checking — the await is a suspension point where another
    coroutine can invalidate the check."""
    src = '''
class Booth:
    async def grant(self, who):
        if self.holder is None:
            await self.bookkeeping(who)
            self.holder = who
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-R001" in rules, rules


def test_raceguard_r001_clean_twins():
    """Re-checking after the await, or holding a lock around the whole
    check+act, clears the finding."""
    rechecked = '''
class Booth:
    async def grant(self, who):
        if self.holder is None:
            await self.bookkeeping(who)
            if self.holder is None:
                self.holder = who
'''
    locked = '''
class Booth:
    async def grant(self, who):
        async with self._lock:
            if self.holder is None:
                await self.bookkeeping(who)
                self.holder = who
'''
    for src in (rechecked, locked):
        rules = _rules(analyze_source(src, "meshnet/fixture.py"))
        assert "ML-R001" not in rules, rules


def test_raceguard_r002_known_bad_fixture():
    """ML-R002: a create_task handle that is dropped (bare statement) or
    bound to a name never read again — exceptions vanish and asyncio's
    weak reference lets GC cancel the task mid-flight."""
    src = '''
import asyncio

class Svc:
    async def start(self):
        asyncio.create_task(self.loop())
        t = asyncio.create_task(self.other())
        self.ready = True
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert rules.count("ML-R002") == 2, rules


def test_raceguard_r002_clean_twins():
    """Awaiting the handle, reading the bound attribute (cancellation,
    done-callback), or a tracked spawn helper all clear the finding."""
    src = '''
import asyncio

class Svc:
    async def start(self):
        t = asyncio.create_task(self.loop())
        await t
        self._task = asyncio.create_task(self.other())
        self._task.add_done_callback(print)
        self._tasks.spawn(self.third())
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-R002" not in rules, rules


def test_raceguard_r003_known_bad_fixture():
    """ML-R003: a shared container mutated after awaits from two
    distinct coroutine entry points with no lock on any mutation path."""
    src = '''
class Hub:
    async def _handle_join(self, ws, data):
        await self.notify(ws)
        self.subs[data["id"]] = ws

    async def _handle_leave(self, ws, data):
        await self.notify(ws)
        self.subs.pop(data["id"], None)
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-R003" in rules, rules


def test_raceguard_r003_clean_twins():
    """A lock on the mutation paths — or a single entry point — clears
    the finding."""
    locked = '''
class Hub:
    async def _handle_join(self, ws, data):
        async with self._lock:
            await self.notify(ws)
            self.subs[data["id"]] = ws

    async def _handle_leave(self, ws, data):
        async with self._lock:
            await self.notify(ws)
            self.subs.pop(data["id"], None)
'''
    single = '''
class Hub:
    async def _handle_join(self, ws, data):
        await self.notify(ws)
        self.subs[data["id"]] = ws
'''
    for src in (locked, single):
        rules = _rules(analyze_source(src, "meshnet/fixture.py"))
        assert "ML-R003" not in rules, rules


def test_raceguard_r004_known_bad_fixture():
    """ML-R004: awaiting inside iteration over a shared container —
    a mutation during the suspension invalidates the iterator."""
    src = '''
class Hub:
    async def broadcast(self, msg):
        for ws in self.conns:
            await ws.send(msg)
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-R004" in rules, rules


def test_raceguard_r004_clean_twins():
    """Materializing a snapshot (list()/tuple()/sorted()) or holding a
    lock across the loop clears the finding."""
    src = '''
class Hub:
    async def broadcast(self, msg):
        for ws in list(self.conns):
            await ws.send(msg)
        for ws in sorted(self.conns):
            await ws.send(msg)
        async with self._lock:
            for ws in self.conns:
                await ws.send(msg)
'''
    rules = _rules(analyze_source(src, "meshnet/fixture.py"))
    assert "ML-R004" not in rules, rules


def test_seeded_toctou_in_real_node_is_caught():
    """The acceptance seed: rewrite node.py's begin_drain into a
    check-then-act split across the drain await — ML-R001 must fire on
    the real source."""
    src = (PACKAGE_ROOT / "meshnet" / "node.py").read_text()
    seeded = src.replace(
        "        self.drain_source = source\n"
        "        return await self.migration.drain(stop=stop, wait=wait)",
        "        if self.drain_source is None:\n"
        "            await self.migration.drain(stop=stop, wait=wait)\n"
        "            self.drain_source = source\n"
        "        return {}",
        1,
    )
    assert seeded != src, "begin_drain body moved; update the seed"
    assert any(
        f.rule == "ML-R001" and "drain_source" in f.message
        for f in analyze_source(seeded, "meshnet/node.py")
    )


def test_seeded_dropped_handle_in_real_migrate_is_caught():
    """Drop the stop-task binding in migrate.py — the bare create_task
    statement must trip ML-R002 on the real source."""
    src = (PACKAGE_ROOT / "meshnet" / "migrate.py").read_text()
    seeded = src.replace(
        "self._stop_task = asyncio.create_task", "asyncio.create_task", 1
    )
    assert seeded != src, "migrate.py stop-task spawn moved; update the seed"
    assert any(
        f.rule == "ML-R002" for f in analyze_source(seeded, "meshnet/migrate.py")
    )


def test_toctou_demo_suppression_and_static_detection():
    """The fuzzer's deliberately raceable demo (simnet/fuzz.py) ships
    with a reasoned suppression — stripping it must expose ML-R001, so
    the SAME bug the fuzzer provokes dynamically is also caught
    statically."""
    fuzz_py = PACKAGE_ROOT / "simnet" / "fuzz.py"
    src = fuzz_py.read_text()
    assert "ignore[ML-R001]" in src
    assert analyze_paths([fuzz_py]) == []
    stripped = src.replace("# meshlint: ignore[ML-R001]", "# stripped", 1)
    assert any(
        f.rule == "ML-R001" and "holder" in f.message
        for f in analyze_source(stripped, "simnet/fuzz.py")
    )


def test_raceguard_scope_and_catalog():
    from bee2bee_tpu.analysis.raceguard import RaceGuardPass

    p = RaceGuardPass()
    for path in ("meshnet/node.py", "router/policy.py", "fleet/controller.py",
                 "web/bridge.py", "api.py", "simnet/fuzz.py"):
        assert p.applies(path), path
    for path in ("engine/scheduler.py", "models/llama.py", "bench.py"):
        assert not p.applies(path), path
    for rule in ("ML-R001", "ML-R002", "ML-R003", "ML-R004"):
        assert rule in rule_catalog(), rule
