"""Deterministic fleet-scale simulation (bee2bee_tpu/simnet/).

These are the sim-backed REGRESSION tests for the fleet claims: every
scenario runs hundreds of FakeService-backed P2PNode control planes on
one loop in VIRTUAL time (the wall cost is only the python work), and
the determinism contract — same seed ⇒ bit-identical event trace and
fleet decision journal — is itself a pinned test, not a comment.

Scale notes: the 200-node replay pair is the single most expensive test
in the file (~2 × (bootstrap + 3 gossip ticks)); everything else rides
smaller fleets. All timeouts are wall-clock caps via the conftest
``async_timeout`` marker — virtual time inside is unbounded-cheap.
"""

from __future__ import annotations

import asyncio
import statistics

import pytest

from bee2bee_tpu.simnet import (
    FleetSim,
    KademliaModel,
    LinkProfile,
    SimNet,
    VirtualClock,
)

pytestmark = pytest.mark.filterwarnings("ignore::RuntimeWarning")


# ------------------------------------------------------------ virtual clock


async def test_virtual_clock_orders_sleepers_and_costs_no_wall_time():
    clock = VirtualClock()
    t0 = clock.time()
    order: list[str] = []

    async def napper(tag: str, delay: float):
        await clock.sleep(delay)
        order.append(tag)

    tasks = [
        asyncio.ensure_future(napper("c", 0.3)),
        asyncio.ensure_future(napper("a", 0.1)),
        asyncio.ensure_future(napper("b", 0.2)),
    ]
    await clock.run_for(1.0)
    assert order == ["a", "b", "c"]
    assert clock.time() == pytest.approx(t0 + 1.0)
    for t in tasks:
        assert t.done()


async def test_virtual_clock_call_at_fires_in_deadline_order():
    clock = VirtualClock()
    fired: list[int] = []
    now = clock.time()
    clock.call_at(now + 0.2, lambda: fired.append(2))
    clock.call_at(now + 0.1, lambda: fired.append(1))
    clock.call_at(now + 0.1, lambda: fired.append(11))  # FIFO within a tick
    await clock.run_for(0.5)
    assert fired == [1, 11, 2]


# ------------------------------------------------------------ sim transport


async def test_sim_transport_echo_roundtrip():
    clock = VirtualClock()
    net = SimNet(clock, seed=0)

    async def handler(ws):
        async for m in ws:
            await ws.send(f"echo:{m}")

    t_srv = net.transport("10.0.0.1")
    server = await t_srv.serve(handler, "0.0.0.0", 9000)
    t_cli = net.transport("10.0.0.2")
    ws = await t_cli.dial("ws://10.0.0.1:9000")
    fut = asyncio.ensure_future(ws.recv())
    await ws.send('{"type": "ping"}')
    await clock.run_for(1.0)
    assert fut.result() == 'echo:{"type": "ping"}'
    await ws.close()
    server.close()
    await clock.run_for(1.0)


async def test_sim_transport_partition_refuses_dials_and_drops_frames():
    clock = VirtualClock()
    net = SimNet(clock, seed=0)
    net.set_region("10.0.0.1", "east")
    net.set_region("10.0.0.2", "west")

    async def handler(ws):
        async for _ in ws:
            pass

    await net.transport("10.0.0.1").serve(handler, "0.0.0.0", 9000)
    cli = net.transport("10.0.0.2")
    ws = await cli.dial("ws://10.0.0.1:9000")  # pre-partition: fine
    net.partition("east", "west")
    await ws.send("lost")  # black-holed, not an error
    await clock.run_for(1.0)
    with pytest.raises(OSError):
        await cli.dial("ws://10.0.0.1:9000")
    kinds = {e[1] for e in net.trace}
    assert "part" in kinds or "drop" in kinds
    net.heal()
    ws2 = await cli.dial("ws://10.0.0.1:9000")
    assert ws2 is not None


# -------------------------------------------------------- determinism contract


def _fingerprints(trace_fp: str, journal_fp: str) -> tuple[str, str]:
    return trace_fp, journal_fp


async def _replay_run(n: int, seed: int, virtual_s: float) -> tuple[str, str, int]:
    sim = FleetSim(n, seed=seed)
    try:
        await sim.start()
        await sim.run_for(virtual_s)
        journals = sim.journals()
        assert journals, "no controller journal — the comparison would be vacuous"
        assert any(journals.values()), "controller never decided anything"
        return sim.trace_fingerprint(), sim.journal_fingerprint(), len(sim.net.trace)
    finally:
        await sim.stop()


@pytest.mark.async_timeout(420)
async def test_same_seed_200_node_replay_is_bit_identical():
    """THE determinism contract at fleet scale: two runs of the same
    200-node scenario with the same seed produce byte-identical event
    traces AND byte-identical /fleet decision journals. Any wall-clock
    leak, thread race, or unseeded draw in the control plane breaks
    this equality."""
    # 4.5 virtual s: past the lease-lapse claim point (~4 ticks), so the
    # journal comparison is non-vacuous
    t1, j1, n1 = await _replay_run(200, seed=7, virtual_s=4.5)
    t2, j2, n2 = await _replay_run(200, seed=7, virtual_s=4.5)
    assert n1 > 1000, f"trace suspiciously small ({n1} events)"
    assert t1 == t2, "same-seed event traces diverged"
    assert j1 == j2, "same-seed fleet decision journals diverged"


@pytest.mark.async_timeout(120)
async def test_different_seeds_produce_different_schedules():
    """The seed must actually matter: jitter draws reorder deliveries."""
    t1, _, _ = await _replay_run(20, seed=1, virtual_s=5.0)
    t2, _, _ = await _replay_run(20, seed=2, virtual_s=5.0)
    assert t1 != t2, "seed had no observable effect on the schedule"


# ------------------------------------------------------------ fleet claims


@pytest.mark.async_timeout(180)
async def test_gossip_convergence_within_tick_budget_as_n_grows():
    """Telemetry gossip must reach full (observer, subject) coverage in
    a bounded number of ticks regardless of N — the claim that lets the
    router trust its digests fleet-wide. Regression surface for the
    delta-gossip/digest-fanout path."""
    budgets = {}
    for n in (10, 30):
        sim = FleetSim(n, seed=3)
        try:
            await sim.start()
            t0 = sim.clock.time()
            ticks = 0
            while sim.gossip_coverage() < 1.0 and ticks < 8:
                await sim.run_for(sim.ping_interval_s)
                ticks += 1
            assert sim.gossip_coverage() == 1.0, (
                f"gossip never converged at n={n}: "
                f"coverage={sim.gossip_coverage():.3f} after {ticks} ticks"
            )
            budgets[n] = sim.clock.time() - t0
        finally:
            await sim.stop()
    # the budget is ticks, not node count: 3x the fleet must not need 3x
    # the ticks (full mesh: every digest is one hop + relay freshness)
    assert budgets[30] <= budgets[10] + 2 * 1.0, budgets


@pytest.mark.async_timeout(240)
async def test_controller_survives_half_fleet_churn_with_zero_dropped_generations():
    """Kill 50% of a 24-node fleet while generations are in flight on
    the survivors: every in-flight generation on a surviving pair must
    complete, and the controller (a survivor) must keep journaling
    decisions afterwards."""
    sim = FleetSim(24, seed=11)
    try:
        await sim.start()
        # slow the surviving providers so the requests are genuinely
        # in flight when the churn wave hits
        pairs = [(1, 2), (3, 4), (5, 6), (7, 8), (9, 10), (11, 1)]
        for _, b in pairs:
            sim.nodes[b].local_services["fake"].exec_delay_s = 3.0
        futs = [
            asyncio.ensure_future(
                sim.nodes[a].request_generation(
                    sim.nodes[b].peer_id,
                    f"prompt-{k}",
                    model="sim-model",
                    timeout=60.0,
                )
            )
            for k, (a, b) in enumerate(pairs)
        ]
        await sim.run_for(0.5)  # requests on the wire, providers mid-sleep
        assert not any(f.done() for f in futs), "generations finished too early"
        for i in range(12, 24):  # the churn wave: hard kills, no GOODBYE
            await sim.kill(i)
        await sim.run_for(10.0)
        assert all(f.done() for f in futs), "generation still pending after churn"
        for f in futs:
            result = f.result()  # raises if any generation was dropped
            assert result.get("text"), result
        # the controller keeps making decisions after the wave
        before = sum(len(v) for v in sim.journals().values())
        await sim.run_for(3.0)
        after = sum(len(v) for v in sim.journals().values())
        assert after > before, "controller stopped journaling after churn"
    finally:
        await sim.stop()


@pytest.mark.async_timeout(300)
async def test_split_brain_partition_and_heal_at_100_nodes():
    """Region split-brain: black-hole the link between two 50-node
    regions (the middlebox failure mode — connections stay open, frames
    vanish). The health plane must mark every cross-region peer
    unreachable and expire its telemetry digests; on heal, reachability
    and full gossip coverage must recover without operator action."""
    regions = {i: ("east" if i < 50 else "west") for i in range(100)}
    far_of = {}  # node_id index -> far-region peer_id set
    sim = FleetSim(100, seed=5, regions=regions)
    try:
        await sim.start()
        assert sim.mesh_connected()
        for _ in range(6):
            if sim.gossip_coverage() == 1.0:
                break
            await sim.run_for(1.0)
        assert sim.gossip_coverage() == 1.0, "fleet never converged pre-split"
        sim.net.partition("east", "west")
        await sim.run_for(10.0)  # > 3-tick TTL: far side goes stale
        for node in sim.nodes:
            far = {
                p
                for p in node.peers
                if regions[int(p.rsplit("-", 1)[-1])] != node.region
            }
            far_of[node.peer_id] = far
            assert len(far) == 50, (node.peer_id, len(far))
            bad = {
                p for p in far
                if node.peers[p].get("health") != "unreachable"
            }
            assert not bad, (
                f"{node.peer_id}: cross-region peers not marked unreachable: "
                f"{sorted(bad)[:5]}"
            )
            # far-region telemetry digests expired out of the fresh set
            stale_leak = set(node.health.fresh()) & far
            assert not stale_leak, (
                f"{node.peer_id} still trusts far-region digests {stale_leak}"
            )
        # coverage collapses to the intra-region fraction (50·49·2 pairs)
        intra = (50 * 49 * 2) / (100 * 99)
        assert sim.gossip_coverage() == pytest.approx(intra, abs=0.02)
        sim.net.heal()
        deadline = sim.clock.time() + 30.0
        while sim.gossip_coverage() < 1.0 and sim.clock.time() < deadline:
            await sim.run_for(1.0)
        assert sim.gossip_coverage() == 1.0, (
            f"coverage never recovered after heal: {sim.gossip_coverage():.3f}"
        )
        for node in sim.nodes:
            still_dark = {
                p for p in far_of[node.peer_id]
                if node.peers.get(p, {}).get("health") == "unreachable"
            }
            assert not still_dark, (
                f"{node.peer_id}: peers still unreachable post-heal "
                f"{sorted(still_dark)[:5]}"
            )
    finally:
        await sim.stop()


# ------------------------------------------------------------ DHT scaling


def test_dht_lookup_depth_stays_logarithmic_at_500_peers():
    """Kademlia routing-model regression: lookup depth at 500 peers must
    stay in the O(log N) envelope (measured: max 3, mean ~2.1). A
    routing-table regression shows up here as a depth cliff, not as a
    production latency incident."""
    model = KademliaModel(500, seed=3)
    depths = model.sample_depths(50)
    assert max(depths) <= 4, f"lookup depth blew the envelope: {max(depths)}"
    assert statistics.mean(depths) <= 3.0, depths
    # replay-stable: the depth measurement itself is deterministic
    assert KademliaModel(500, seed=3).sample_depths(50) == depths
    # and depth grows (weakly) with fleet size — the model is not flat
    small = statistics.mean(KademliaModel(50, seed=3).sample_depths(50))
    assert statistics.mean(depths) >= small


def test_link_profile_jitter_spans_quanta():
    """The seed only matters if jitter can move a delivery across the
    quantization grid — pin the default relationship so a future 'perf
    tweak' can't silently turn every seed into the same schedule."""
    p = LinkProfile()
    assert p.jitter_s > 0
    clock = VirtualClock()
    net = SimNet(clock, seed=0)
    assert p.jitter_s > net.quantum_s
