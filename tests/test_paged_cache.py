"""Paged KV cache (engine/paged.py + core.forward block_tables path —
the engine's ONLY cache layout since the rectangular cache was deleted):

- token parity between the pool's two attention paths (dense over the
  gathered view vs the ragged paged kernel) across model families
  including GQA/MQA, sliding windows, and the gemma-3
  dual-rope/alternating-mask stack;
- free-list allocator exhaustion -> admission backpressure -> reuse;
- block-level copy-on-write prefix sharing (at most ONE partial-block
  copy per hit), including the donor-retires-first ordering;
- per-step cache reads proportional to LIVE blocks, not
  max_batch * max_seq — the idle-row tax the paged pool exists to kill.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from bee2bee_tpu.engine import EngineConfig, InferenceEngine
from bee2bee_tpu.engine.paged import (
    BlockAllocator,
    PagedPrefixCache,
    ceil_div,
    pow2_at_least,
)

KW = dict(
    max_seq_len=128, dtype="float32", cache_dtype="float32",
    decode_chunk=4, prefill_buckets=(16, 32, 64),
)


def _prompt(seed: int, n: int = 37) -> list[int]:
    return list(np.random.default_rng(seed).integers(3, 500, size=n))


# ------------------------------------------------------------- unit: allocator


def test_block_allocator_alloc_free_refcount():
    a = BlockAllocator(6)  # block 0 reserved -> 5 usable
    got = a.alloc(3)
    assert got is not None and len(set(got)) == 3 and 0 not in got
    assert a.used_count == 3 and a.free_count == 2
    assert a.alloc(3) is None  # all-or-nothing: no partial leak
    assert a.free_count == 2
    a.ref([got[0]])
    assert a.deref([got[0]]) == 0  # still referenced by the row
    assert a.deref(got) == 3  # refs drop to zero -> all freed
    assert a.free_count == 5 and a.hwm == 3
    # freed ids come back out
    again = a.alloc(5)
    assert again is not None and sorted(again) == sorted(range(1, 6))


def test_paged_prefix_cache_pins_and_evicts():
    a = BlockAllocator(8)
    pc = PagedPrefixCache(2, a)
    b1, b2, b3 = a.alloc(2), a.alloc(2), a.alloc(2)
    pc.put([1, 2, 3], b1)
    pc.put([4, 5, 6], b2)
    assert a.refcount(b1[0]) == 2  # pinned on top of the row's ref
    m, blocks = pc.match([1, 2, 3, 9])
    assert m == 3 and tuple(blocks) == tuple(b1)
    # capacity eviction drops the LRU pin ([4,5,6] — match touched [1,2,3])
    pc.put([7, 8, 9], b3)
    assert len(pc) == 2 and a.refcount(b2[0]) == 1
    # rows release; pinned blocks survive until eviction under pressure
    a.deref(b1), a.deref(b2), a.deref(b3)
    assert a.free_count == 2 + 1  # b2 fully freed, b1/b3 pinned...
    assert pc.evict_for_pressure(7)
    assert a.free_count == 7 and len(pc) == 0


def test_pow2_and_ceil_helpers():
    assert [pow2_at_least(n) for n in (1, 2, 3, 5, 8, 9)] == [1, 2, 4, 8, 8, 16]
    assert ceil_div(7, 4) == 2 and ceil_div(8, 4) == 2


# -------------------------------------------------------------- token parity


@pytest.mark.parametrize(
    "name",
    [
        "tiny-llama",   # GQA (2 kv heads / 4 q heads)
        "tiny-gemma",   # MQA single kv head
        "tiny-gemma3",  # alternating local/global masks + dual-theta rope,
                        # sliding window 4 < prompt
        # extended coverage outside the tier-1 time budget:
        pytest.param("tiny-qwen", marks=pytest.mark.slow),     # qkv bias
        pytest.param("tiny-mistral", marks=pytest.mark.slow),  # window only
    ],
)
def test_paged_dense_vs_ragged_flash_greedy(name):
    """Family sweep over THE two pool attention paths: dense attention
    over the gathered block view vs the ragged paged kernel reading the
    pool directly (attention='flash') — token-for-token greedy parity,
    including the gemma-3 alternating local/global masks and dual-theta
    rope, which ride the kernel via the dense path's own per-layer mask."""
    prompt = _prompt(0, n=21)  # crosses a block boundary (block_size 16)
    ref = InferenceEngine(name, engine_config=EngineConfig(**KW))
    want = ref.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
    ref.close()

    eng = InferenceEngine(
        name, engine_config=EngineConfig(attention="flash", **KW)
    )
    got = eng.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
    eng.close()
    assert got == want


# ------------------------------------------------------ int8 pool parity


INT8_FAMILIES = [
    "tiny-llama",   # GQA (2 kv heads / 4 q heads)
    "tiny-gemma",   # MQA single kv head
    "tiny-gemma3",  # alternating local/global masks + dual-theta rope
    pytest.param("tiny-qwen", marks=pytest.mark.slow),     # qkv bias
    pytest.param("tiny-mistral", marks=pytest.mark.slow),  # window only
]


@pytest.mark.parametrize("name", INT8_FAMILIES)
def test_paged_int8_pool_greedy_parity(name):
    """ISSUE 12 family sweep: the int8 pool (quantize-on-write + in-read
    dequant) serves greedy decode within tolerance of the full-precision
    pool, and its TWO read paths — dense attention over the dequantized
    gathered view vs the ragged kernel dequantizing per gathered block —
    agree token-for-token EXACTLY (they read the same quantized bytes
    under the same scales, so any divergence is a dequant bug, not
    quantization noise)."""
    prompt = _prompt(0, n=21)  # crosses a block boundary (block_size 16)
    ref = InferenceEngine(name, engine_config=EngineConfig(**KW))
    want = ref.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
    ref.close()

    kw8 = dict(KW, cache_dtype="int8")
    dense = InferenceEngine(name, engine_config=EngineConfig(**kw8))
    got_dense = dense.generate(
        prompt, max_new_tokens=10, temperature=0.0
    ).token_ids
    dense.close()
    flash = InferenceEngine(
        name, engine_config=EngineConfig(attention="flash", **kw8)
    )
    got_flash = flash.generate(
        prompt, max_new_tokens=10, temperature=0.0
    ).token_ids
    flash.close()
    assert got_dense == got_flash, "int8 dense vs ragged-kernel dequant split"
    # bf16-vs-int8 tolerance: int8 KV noise (~0.8% of a page's amax) may
    # legitimately flip a near-tied greedy argmax late in the rollout —
    # but not more than a couple of tokens of ten on these fixed seeds
    mismatches = sum(a != b for a, b in zip(want, got_dense))
    assert len(got_dense) == len(want) and mismatches <= 2, (
        f"int8 pool drifted {mismatches}/10 tokens vs full precision: "
        f"{got_dense} vs {want}"
    )


def test_paged_int8_prefix_cow_and_block_recycling_stay_exact():
    """The int8 pool's bookkeeping invariants: CoW prefix sharing copies
    a page's SCALE with its bytes (repeat prompts decode identically),
    and a recycled block's zeroed scale entry means pool churn cannot
    bleed one tenant's amax into the next (repeat of the first prompt
    still matches after unrelated traffic reused its freed blocks)."""
    kw8 = dict(KW, cache_dtype="int8")
    prompt = _prompt(2, n=24)
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, prefix_cache_entries=4, **kw8),
    )
    try:
        st = eng.scheduler.stats
        a = eng.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
        # churn the pool so freed blocks are recycled under new scales
        eng.generate(_prompt(9, n=30), max_new_tokens=10, temperature=0.0)
        b = eng.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
        c = eng.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
        assert a == b == c
        assert st.prefix_hits >= 2
        assert st.paged_blocks_copied >= 1  # the CoW partial-block copy ran
    finally:
        eng.close()


@pytest.mark.slow
def test_paged_matches_rectangular_sampled_and_penalized():
    """Same rng seed => same token stream: the sampled path reads the same
    logits, and penalty counts ride independently of the cache layout."""
    prompt = _prompt(3)
    kwargs = dict(max_new_tokens=10, temperature=0.9, top_k=40, top_p=0.95,
                  repetition_penalty=1.3)
    ref = InferenceEngine("tiny-llama", engine_config=EngineConfig(**KW))
    want = ref.generate(prompt, **kwargs).token_ids
    ref.close()
    eng = InferenceEngine(
        "tiny-llama", engine_config=EngineConfig(paged=True, **KW)
    )
    got = eng.generate(prompt, **kwargs).token_ids
    eng.close()
    assert got == want


def test_paged_concurrent_batch_matches_sequential():
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, max_batch=8, **KW),
    )
    try:
        prompts = [_prompt(10 + i, n=12 + 3 * i) for i in range(4)]
        budgets = [6, 8, 12, 16]
        sequential = [
            eng.generate(p, max_new_tokens=m, temperature=0.0).token_ids
            for p, m in zip(prompts, budgets)
        ]
        results: list = [None] * 4

        def run(i):
            results[i] = eng.generate(
                prompts[i], max_new_tokens=budgets[i], temperature=0.0
            )

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(4):
            assert results[i].token_ids == sequential[i], f"row {i} diverged"
        assert eng.scheduler.stats.peak_active >= 2
        # everything retired -> every block back on the free list
        assert eng.scheduler.stats.paged_blocks_in_use == 0
    finally:
        eng.close()


@pytest.mark.slow  # the chunked-prefill composition also rides tier-1 via
# test_paged_chat_turn_extension_matches_fresh_engine (prefill_chunk=16)
def test_paged_with_chunked_prefill_matches():
    prompt = _prompt(5, n=50)
    ref = InferenceEngine("tiny-llama", engine_config=EngineConfig(**KW))
    want = ref.generate(prompt, max_new_tokens=8, temperature=0.0).token_ids
    ref.close()
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, prefill_chunk=16, **KW),
    )
    got = eng.generate(prompt, max_new_tokens=8, temperature=0.0).token_ids
    eng.close()
    assert got == want


# ------------------------------------------------- exhaustion / backpressure


def test_pool_exhaustion_queues_and_reuses_freed_blocks():
    """A pool sized for ~1.5 rows must still complete 4 concurrent
    requests — admissions wait for retirements to free blocks, and the
    high-water mark proves the free list was recycled, not grown."""
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(
            paged=True, max_batch=4, kv_pool_blocks=9, kv_block_size=8,
            max_seq_len=64, dtype="float32", cache_dtype="float32",
            decode_chunk=4, prefill_buckets=(16,),
        ),
    )
    try:
        results: list = [None] * 4

        def run(i):
            results[i] = eng.generate(
                [5 + i] * 20, max_new_tokens=10, temperature=0.0
            )

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(r is not None and r.new_tokens == 10 for r in results)
        st = eng.scheduler.stats
        assert st.paged_alloc_waits > 0, "pool never backpressured"
        assert st.paged_blocks_hwm <= 8  # never exceeded the pool
        assert st.paged_blocks_in_use == 0  # free-list fully recovered
        # the engine keeps serving after the contention
        r = eng.generate([9] * 10, max_new_tokens=4, temperature=0.0)
        assert r.new_tokens == 4
    finally:
        eng.close()


def test_concurrent_admission_under_pool_pressure_completes_or_raises():
    """Hammer submit with more simultaneous requests than the pool can
    hold, including two that can NEVER fit: every request either
    completes its full budget or raises the _PoolExhausted-derived error
    — no hangs, and after the drain every block is back on the free list
    (leak check against the allocator's own initial free count)."""
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(
            paged=True, max_batch=4, kv_pool_blocks=9, kv_block_size=8,
            max_seq_len=96, dtype="float32", cache_dtype="float32",
            decode_chunk=4, prefill_buckets=(16, 32, 64, 96),
        ),
    )
    try:
        initial_free = eng.scheduler._alloc.free_count
        # 8 fitting requests (4 blocks each at completion: 20 prompt + 10
        # new = 30 positions) racing 2 that exceed the whole pool
        # (80 prompt + 10 new = 90 positions > 64 the pool covers)
        sizes = [20] * 8 + [80] * 2
        results: list = [None] * len(sizes)

        def run(i):
            try:
                results[i] = eng.generate(
                    [3 + i] * sizes[i], max_new_tokens=10, temperature=0.0
                )
            except RuntimeError as e:
                results[i] = e

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(len(sizes))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        assert all(not t.is_alive() for t in threads), "a request hung"
        for i, r in enumerate(results):
            if isinstance(r, RuntimeError):
                assert "exhausted" in str(r), f"req {i}: untyped error {r}"
                assert sizes[i] == 80, f"fitting req {i} was failed: {r}"
            else:
                assert r is not None and r.new_tokens == 10, f"req {i}: {r}"
        # the two impossible requests failed, everything else completed
        assert sum(isinstance(r, RuntimeError) for r in results) == 2
        st = eng.scheduler.stats
        assert st.paged_blocks_in_use == 0, "leaked block references"
        assert eng.scheduler._alloc.free_count == initial_free, (
            "free list did not recover to its initial size"
        )
        # and the engine still serves after the stampede
        assert eng.generate([7] * 12, max_new_tokens=4).new_tokens == 4
    finally:
        eng.close()


def test_request_larger_than_pool_fails_cleanly():
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(
            paged=True, kv_pool_blocks=4, kv_block_size=8, **KW
        ),
    )
    try:
        with pytest.raises(RuntimeError, match="exhausted"):
            eng.generate([1] * 40, max_new_tokens=4, temperature=0.0)
        # the failure is per-request: a fitting one still serves
        r = eng.generate([2] * 10, max_new_tokens=4, temperature=0.0)
        assert r.new_tokens == 4
    finally:
        eng.close()


# --------------------------------------------------- prefix sharing (CoW)


def test_paged_prefix_hit_copies_at_most_one_block():
    prompt = _prompt(0, n=24)
    ref = InferenceEngine("tiny-llama", engine_config=EngineConfig(**KW))
    want = ref.generate(prompt, max_new_tokens=8, temperature=0.0).token_ids
    ref.close()

    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, prefix_cache_entries=4, **KW),
    )
    try:
        st = eng.scheduler.stats
        first = eng.generate(prompt, max_new_tokens=8, temperature=0.0).token_ids
        assert st.prefix_hits == 0 and st.paged_blocks_copied == 0
        second = eng.generate(prompt, max_new_tokens=8, temperature=0.0).token_ids
        # 24-token repeat matches 23 (cap n-1): 23//16=1 block shared,
        # ONE partial block (tokens 16..22) copied
        assert st.prefix_hits == 1
        assert st.prefix_tokens_saved == len(prompt) - 1
        assert st.paged_blocks_copied == 1
        assert first == want and second == want
    finally:
        eng.close()


def test_paged_prefix_block_aligned_hit_copies_nothing():
    """A match on a block boundary shares every block: zero CoW copies."""
    bs = 16
    prompt = _prompt(1, n=2 * bs)  # 32 tokens
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(
            paged=True, prefix_cache_entries=4, kv_block_size=bs, **KW
        ),
    )
    try:
        st = eng.scheduler.stats
        r1 = eng.generate(prompt, max_new_tokens=6, temperature=0.0).token_ids
        # turn-2 transcript extends past the cached 32 tokens: the match is
        # the FULL first turn (32 = 2 whole blocks) -> pure sharing
        turn2 = prompt + r1 + _prompt(2, n=10)
        eng.generate(turn2, max_new_tokens=6, temperature=0.0)
        assert st.prefix_hits == 1
        assert st.prefix_tokens_saved == len(prompt)
        assert st.paged_blocks_copied == 0
    finally:
        eng.close()


def test_paged_chat_turn_extension_matches_fresh_engine():
    rng = np.random.default_rng(1)
    turn1 = list(rng.integers(3, 500, size=30))
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(
            paged=True, prefix_cache_entries=4, prefill_chunk=16, **KW
        ),
    )
    try:
        r1 = eng.generate(turn1, max_new_tokens=6, temperature=0.0)
        turn2 = turn1 + r1.token_ids + list(rng.integers(3, 500, size=10))
        r2 = eng.generate(turn2, max_new_tokens=6, temperature=0.0)
        assert eng.scheduler.stats.prefix_hits == 1
    finally:
        eng.close()

    fresh = InferenceEngine("tiny-llama", engine_config=EngineConfig(**KW))
    try:
        want = fresh.generate(turn2, max_new_tokens=6, temperature=0.0).token_ids
    finally:
        fresh.close()
    assert r2.token_ids == want


def test_paged_prefix_survives_donor_retirement():
    """The donor retires (its row refs drop) BEFORE the borrower admits:
    the entry's pins must keep the shared blocks alive and intact."""
    prompt = _prompt(2, n=24)
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, prefix_cache_entries=4, **KW),
    )
    try:
        st = eng.scheduler.stats
        a = eng.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
        # donor fully retired; its generation-only blocks are back on the
        # free list, the prompt blocks survive via the entry's pins
        assert st.paged_blocks_in_use > 0  # pinned prompt blocks remain
        # churn the pool so freed blocks get reused (stale-content hazard)
        eng.generate(_prompt(9, n=30), max_new_tokens=10, temperature=0.0)
        b = eng.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
        c = eng.generate(prompt, max_new_tokens=10, temperature=0.0).token_ids
        assert a == b == c
        assert st.prefix_hits >= 2
    finally:
        eng.close()


def test_reanchored_prefill_leaves_shared_blocks_read_only():
    """A whole-prompt bucket larger than the remaining capacity re-anchors
    the prefill window BELOW the CoW share point (pos = max(0, S - bucket)
    < start). The re-fed positions must NOT rewrite the donor's shared
    blocks (the write floor drops them): the donor's cached entry stays
    byte-identical and the borrower still matches a fresh engine."""
    kw = dict(max_seq_len=64, dtype="float32", cache_dtype="float32",
              decode_chunk=4, prefill_buckets=(16, 64))
    donor = _prompt(4, n=20)
    borrower = donor + _prompt(5, n=40)  # 60 tokens: start=20, bucket=64
    # -> re-anchor to pos=0 < start=20

    fresh = InferenceEngine("tiny-llama", engine_config=EngineConfig(**kw))
    want_d = fresh.generate(donor, max_new_tokens=6, temperature=0.0).token_ids
    want_b = fresh.generate(borrower, max_new_tokens=3, temperature=0.0).token_ids
    fresh.close()

    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, prefix_cache_entries=4, **kw),
    )
    try:
        d1 = eng.generate(donor, max_new_tokens=6, temperature=0.0).token_ids
        got_b = eng.generate(borrower, max_new_tokens=3, temperature=0.0).token_ids
        assert eng.scheduler.stats.prefix_hits == 1  # the re-anchored admit
        # donor's pinned blocks survived the borrower's re-fed window
        d2 = eng.generate(donor, max_new_tokens=6, temperature=0.0).token_ids
        assert d1 == d2 == want_d
        assert got_b == want_b
    finally:
        eng.close()


def test_paged_prefix_entries_reclaimed_under_pressure():
    """Pinned prefix blocks are reclaimable, not leaked: filling the pool
    with pinned prompts must not starve new admissions."""
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(
            paged=True, prefix_cache_entries=8, max_batch=2,
            kv_pool_blocks=12, kv_block_size=8,
            max_seq_len=64, dtype="float32", cache_dtype="float32",
            decode_chunk=4, prefill_buckets=(16,),
        ),
    )
    try:
        for seed in range(5):  # each pins ~3 blocks; pool has 11 usable
            r = eng.generate(
                _prompt(seed, n=20), max_new_tokens=6, temperature=0.0
            )
            # completed (possibly at a natural EOS) — never starved
            assert r.new_tokens >= 1 and r.finish_reason != "error"
    finally:
        eng.close()


# ------------------------------------------------ live-block proportionality


def test_cache_reads_scale_with_live_blocks_not_capacity():
    """The acceptance property: with max_batch=8 and ONE short active
    request, the decode gather reads a few live blocks per step — not the
    rectangular bsz * ceil(max_seq/block) equivalent. Pinned on the
    resize-ladder path: the sticky bucket (docs/PERF.md "Decode hot
    loop") deliberately holds the retired batch's width through its
    idle-hysteresis window, so the lone request would gather across the
    held 8-row bucket — the documented trace-stability-for-read-width
    trade, not a violation of this property."""
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, max_batch=8,
                                   batch_sticky=False, **KW),
    )
    try:
        # warm the batch up to 8 rows so the engine has seen full occupancy
        threads = [
            threading.Thread(
                target=lambda i=i: eng.generate(
                    _prompt(i, n=16), max_new_tokens=8, temperature=0.0
                )
            )
            for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # now ONE active request: per-step reads must track ITS blocks
        eng.generate(_prompt(99, n=16), max_new_tokens=12, temperature=0.0)
        st = eng.scheduler.stats
        bs = eng.engine_cfg.kv_block_size
        rect_equiv = 8 * ceil_div(eng.max_seq_len, bs)  # rectangular tax
        assert st.paged_blocks_read_last_step <= 2 * st.paged_live_blocks + 2
        assert st.paged_blocks_read_last_step < rect_equiv / 4, (
            f"read {st.paged_blocks_read_last_step} blocks/step with one "
            f"active row vs rectangular-equivalent {rect_equiv}"
        )
    finally:
        eng.close()


@pytest.mark.slow
def test_paged_parity_on_tp_mesh():
    """The pool carries the kv-head `model` sharding
    (partition.paged_cache_spec): TP serving over gathered blocks must
    match the rectangular TP path token-for-token, including the MQA
    kv-replication override."""
    import jax

    from bee2bee_tpu.parallel import MeshSpec, build_mesh

    kw = dict(max_seq_len=64, dtype="float32", cache_dtype="float32",
              decode_chunk=4, max_batch=2, prefill_buckets=(16,))
    for name, spec in (("tiny-llama", MeshSpec(data=2, model=2)),
                       ("tiny-gemma", MeshSpec(model=4))):  # MQA: Hkv=1
        mesh = build_mesh(spec, devices=jax.devices()[:4])
        ref = InferenceEngine(name, mesh=mesh,
                              engine_config=EngineConfig(**kw))
        want = ref.generate([5, 17, 99, 42], max_new_tokens=6,
                            temperature=0.0).token_ids
        ref.close()
        eng = InferenceEngine(name, mesh=mesh,
                              engine_config=EngineConfig(paged=True, **kw))
        got = eng.generate([5, 17, 99, 42], max_new_tokens=6,
                           temperature=0.0).token_ids
        eng.close()
        assert got == want, name


@pytest.mark.slow
def test_paged_int8_parity_on_tp_mesh():
    """The int8 pool's sharded read paths agree on a TP mesh: the
    quantized ragged kernel runs per-shard via shard_map with the scale
    operands sharded like the pool's kv-head dim (MQA replication
    included) — greedy parity vs the int8 dense gathered-view engine on
    the same mesh."""
    import jax

    from bee2bee_tpu.parallel import MeshSpec, build_mesh

    kw = dict(max_seq_len=64, dtype="float32", cache_dtype="int8",
              decode_chunk=4, max_batch=2, prefill_buckets=(16,))
    mesh = build_mesh(MeshSpec(model=4), devices=jax.devices()[:4])
    ref = InferenceEngine("tiny-gemma", mesh=mesh,
                          engine_config=EngineConfig(**kw))
    want = ref.generate([5, 17, 99, 42], max_new_tokens=6,
                        temperature=0.0).token_ids
    ref.close()
    eng = InferenceEngine("tiny-gemma", mesh=mesh,
                          engine_config=EngineConfig(attention="flash", **kw))
    got = eng.generate([5, 17, 99, 42], max_new_tokens=6,
                       temperature=0.0).token_ids
    eng.close()
    assert got == want


def test_paged_composes_with_flash_and_auto():
    """The mode matrix is gone: the pool is the only cache layout and
    attention='flash' (the ragged paged kernel) serves it directly —
    greedy parity with the dense gathered-view path, same pool counters.
    auto still resolves to dense on CPU (interpret-mode pallas would be
    slower than the fused dense einsum)."""
    prompt = _prompt(7, n=21)
    dense = InferenceEngine(
        "tiny-llama", engine_config=EngineConfig(paged=True, **KW)
    )
    want = dense.generate(prompt, max_new_tokens=8, temperature=0.0).token_ids
    dense.close()
    eng = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, attention="flash", **KW),
    )
    got = eng.generate(prompt, max_new_tokens=8, temperature=0.0).token_ids
    st = eng.scheduler.stats
    assert got == want
    assert st.paged_blocks_in_use == 0  # released at retirement
    eng.close()
    auto = InferenceEngine(
        "tiny-llama",
        engine_config=EngineConfig(paged=True, attention="auto", **KW),
    )
    assert auto.engine_cfg.attention == "dense"
    auto.close()
