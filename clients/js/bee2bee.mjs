/**
 * bee2bee-tpu JS client SDK.
 *
 * The reference ships a JS SDK (app/src/api/index.js) that targets a v1
 * API its own gateway never implemented; this one targets the REAL
 * shipped surfaces (the same routes the Python SDK bee2bee_tpu/client.py
 * wraps and the test suite exercises):
 *
 *   - NodeClient:    a node's HTTP gateway  (bee2bee_tpu/api.py)
 *   - GatewayClient: the web tier           (bee2bee_tpu/web/gateway.py)
 *
 * Zero dependencies — browser fetch / Node >= 18 fetch. ESM.
 *
 *   import { NodeClient, GatewayClient } from "./bee2bee.mjs";
 *   const node = new NodeClient("http://localhost:4002", { apiKey: "..." });
 *   await node.status();
 *   await node.generate("hello", { onChunk: (t) => process.stdout.write(t) });
 */

async function readJsonLines(response, onObject) {
  const reader = response.body.getReader();
  const decoder = new TextDecoder();
  let buf = "";
  try {
  for (;;) {
    const { done, value } = await reader.read();
    if (done) break;
    buf += decoder.decode(value, { stream: true });
    let nl;
    while ((nl = buf.indexOf("\n")) >= 0) {
      const line = buf.slice(0, nl).trim();
      buf = buf.slice(nl + 1);
      if (!line) continue;
      let obj;
      try {
        obj = JSON.parse(line); // only the parse is guarded:
      } catch {
        continue; /* garbled line — but onObject's throws must propagate */
      }
      onObject(obj);
    }
  }
  const tail = buf.trim();
  if (tail) {
    let obj;
    try {
      obj = JSON.parse(tail);
    } catch {
      return;
    }
    onObject(obj);
  }
  } finally {
    // an onObject throw (stream error) must not leak the connection for
    // the 300 s abort window
    try {
      await reader.cancel();
    } catch {
      /* already closed */
    }
  }
}

// the web gateway reports failures INSIDE its already-200 chunked stream
// (web/gateway.py appends "\n\n[Error]: ..."): surface them as rejections
function throwOnGatewayError(text) {
  const marker = "\n\n[Error]: ";
  const idx = text.lastIndexOf(marker);
  if (idx !== -1) {
    const err = new Error(`gateway error: ${text.slice(idx + marker.length).trim()}`);
    err.partialText = text.slice(0, idx);
    throw err;
  }
  return text;
}

export class NodeClient {
  constructor(baseUrl, { apiKey = null, timeoutMs = 300000 } = {}) {
    this.baseUrl = baseUrl.replace(/\/+$/, "");
    this.headers = { "Content-Type": "application/json" };
    if (apiKey) this.headers["X-API-KEY"] = apiKey;
    this.timeoutMs = timeoutMs;
  }

  async _get(path) {
    const r = await fetch(this.baseUrl + path, {
      headers: this.headers,
      signal: AbortSignal.timeout(this.timeoutMs),
    });
    if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
    return r.json();
  }

  async _post(path, body, { stream = false } = {}) {
    const r = await fetch(this.baseUrl + path, {
      method: "POST",
      headers: this.headers,
      body: JSON.stringify(body),
      signal: AbortSignal.timeout(this.timeoutMs),
    });
    if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
    return stream ? r : r.json();
  }

  status() {
    return this._get("/");
  }
  peers() {
    return this._get("/peers");
  }
  providers() {
    return this._get("/providers");
  }
  connect(addrOrLink) {
    return this._post("/connect", { addr: addrOrLink });
  }

  /** Non-streaming chat; resolves to the result object. `sampling`
   *  forwards extra knobs verbatim (top_k, top_p, repetition_penalty,
   *  presence_penalty, frequency_penalty) — parity with generate(). */
  chat(prompt, { model = null, maxNewTokens = null, temperature = null, sampling = {} } = {}) {
    // sampling spreads FIRST so reserved keys always win
    const body = { ...sampling, prompt, model, stream: false };
    if (maxNewTokens != null) body.max_new_tokens = maxNewTokens;
    if (temperature != null) body.temperature = temperature;
    return this._post("/chat", body);
  }

  /** Streaming generate; onChunk(text) per piece; resolves to full text.
   *  `sampling` forwards extra knobs verbatim (top_k, top_p,
   *  repetition_penalty, presence_penalty, frequency_penalty). */
  async generate(prompt, { model = null, maxNewTokens = null, temperature = null, onChunk = null, sampling = {} } = {}) {
    const body = { ...sampling, prompt, model, stream: true };
    if (maxNewTokens != null) body.max_new_tokens = maxNewTokens;
    if (temperature != null) body.temperature = temperature;
    const r = await this._post("/chat", body, { stream: true });
    const parts = [];
    await readJsonLines(r, (obj) => {
      if (obj.status === "error") throw new Error(obj.message || "stream error");
      if (obj.text) {
        parts.push(obj.text);
        if (onChunk) onChunk(obj.text);
      }
    });
    return parts.join("");
  }
}

export class GatewayClient {
  constructor(baseUrl, { timeoutMs = 300000 } = {}) {
    this.baseUrl = baseUrl.replace(/\/+$/, "");
    this.timeoutMs = timeoutMs;
  }

  async _get(path) {
    const r = await fetch(this.baseUrl + path, {
      signal: AbortSignal.timeout(this.timeoutMs),
    });
    if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
    return r.json();
  }

  status() {
    return this._get("/api/p2p/status");
  }
  globalMetrics() {
    return this._get("/api/p2p/global_metrics");
  }

  async register(joinLink) {
    const r = await fetch(this.baseUrl + "/api/p2p/register", {
      method: "POST",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify({ link: joinLink }),
      signal: AbortSignal.timeout(this.timeoutMs),
    });
    if (!r.ok) throw new Error(`register: HTTP ${r.status}`);
    return r.json();
  }

  /** The gateway streams raw text chunks (not JSON lines). */
  async generate(prompt, { model = null, targetNode = null, maxNewTokens = null, temperature = null, onChunk = null } = {}) {
    const body = { prompt, model };
    if (targetNode) body.targetNode = targetNode;
    if (maxNewTokens != null) body.max_new_tokens = maxNewTokens;
    if (temperature != null) body.temperature = temperature;
    const r = await fetch(this.baseUrl + "/api/p2p/generate", {
      method: "POST",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify(body),
      signal: AbortSignal.timeout(this.timeoutMs),
    });
    if (!r.ok) throw new Error(`generate: HTTP ${r.status}`);
    const reader = r.body.getReader();
    const decoder = new TextDecoder();
    const parts = [];
    for (;;) {
      const { done, value } = await reader.read();
      if (done) break;
      const text = decoder.decode(value, { stream: true });
      parts.push(text);
      if (onChunk) onChunk(text);
    }
    return throwOnGatewayError(parts.join(""));
  }
}
