"""Tracing and observability: request spans + cross-node trace propagation
+ on-demand device profiles.

The reference has NO tracing (SURVEY §5) — the closest artifacts are
per-request latency_ms (reference services.py:97-105) and ping RTTs
(reference p2p_runtime.py:544-557). This module is the required upgrade:

- `Tracer`: a lock-guarded ring buffer of completed `Span`s with nested
  span support (contextvar parent), percentile aggregation per span name,
  and zero dependencies. One process-global instance via `get_tracer()`.
- `Span` context manager works in sync and async code and never throws:
  tracing must not take down the serving path.
- **Trace context propagation**: every span carries a `trace_id` (opened
  fresh at the first span of a request, inherited inside it).
  `inject_trace(frame)` stamps the current (trace_id, span_id) onto a wire
  frame as the optional `trace_ctx` key; the receiving hop calls
  `extract_trace(data)` + `use_trace_ctx(ctx)` so its spans parent under
  the ORIGINATING request across nodes. `/trace?trace_id=` on any node
  returns its local fragment; `stitch_trace()` merges fragments from
  several nodes into one cross-node timeline.
- `device_profile()`: wraps `jax.profiler.trace` so one call captures an
  XLA device trace viewable in TensorBoard/Perfetto.

Spans are cheap (monotonic clock + dict append) and bounded (ring
buffer), so they stay on in production; mesh nodes surface them at the
gateway's `/trace` route. Span NAMES are literal dotted constants —
meshlint ML-T001 rejects dynamically-built names (request-varying names
would defeat the per-name aggregation and explode cardinality).
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator

from .protocol import TRACE_CTX
from .utils import new_id

_current_span: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "bee2bee_current_span", default=None
)
_current_trace: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "bee2bee_current_trace", default=None
)


@dataclass
class Span:
    name: str
    span_id: str = field(default_factory=lambda: new_id("span"))
    parent_id: str | None = None
    trace_id: str | None = None
    start_ms: float = 0.0
    duration_ms: float = -1.0  # -1 while open
    attrs: dict[str, Any] = field(default_factory=dict)
    error: str | None = None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": round(self.duration_ms, 3),
            "attrs": self.attrs,
            "error": self.error,
        }


@dataclass(frozen=True)
class TraceContext:
    """The wire-portable half of a span: enough for a remote hop to parent
    its own spans under the originating request."""

    trace_id: str
    span_id: str

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_wire(cls, obj: Any) -> "TraceContext | None":
        if (
            isinstance(obj, dict)
            and isinstance(obj.get("trace_id"), str)
            and isinstance(obj.get("span_id"), str)
        ):
            return cls(obj["trace_id"], obj["span_id"])
        return None


def current_trace_ctx() -> TraceContext | None:
    """The (trace_id, span_id) pair of the innermost open span, or None
    outside any span."""
    tid, sid = _current_trace.get(), _current_span.get()
    if tid is None or sid is None:
        return None
    return TraceContext(tid, sid)


def inject_trace(fields: dict) -> dict:
    """Stamp the current trace context onto a wire frame/fields dict as
    the optional `trace_ctx` key (declared in analysis/schema.py; the
    reference mesh ignores unknown keys, so frames stay wire-compatible).
    No-op outside a span — never throws."""
    try:
        ctx = current_trace_ctx()
        if ctx is not None:
            fields[TRACE_CTX] = ctx.to_wire()
    except Exception:  # noqa: BLE001 — telemetry never breaks the wire path
        pass
    return fields


def extract_trace(data: dict) -> TraceContext | None:
    """Read a `trace_ctx` key off a received frame; None when absent or
    malformed (old peers / non-instrumented senders) — never throws."""
    try:
        return TraceContext.from_wire(data.get(TRACE_CTX))
    except Exception:  # noqa: BLE001 — a bad frame must not kill a handler
        return None


@contextmanager
def use_trace_ctx(ctx: TraceContext | None):
    """Run a block under a remote trace context: spans opened inside carry
    ctx.trace_id and parent under ctx.span_id. ctx=None is a no-op, so
    handlers can call this unconditionally."""
    if ctx is None:
        yield
        return
    t_trace = _current_trace.set(ctx.trace_id)
    t_span = _current_span.set(ctx.span_id)
    try:
        yield
    finally:
        _current_span.reset(t_span)
        _current_trace.reset(t_trace)


class Tracer:
    """Bounded in-memory span collector; thread-safe; never raises."""

    def __init__(self, capacity: int = 2048):
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._epoch = time.time() * 1000.0 - time.monotonic() * 1000.0
        self.counters: dict[str, int] = {}
        # completion listeners (health.FlightRecorder): called with each
        # closed Span outside the lock; listener errors are swallowed —
        # an observability consumer must never fail the traced code path
        self._listeners: list = []

    def add_listener(self, fn) -> None:
        """Subscribe to span completions (idempotent per function)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        trace_id = _current_trace.get()
        trace_token = None
        if trace_id is None:  # first span of a request: open a new trace
            trace_id = new_id("trace")
            trace_token = _current_trace.set(trace_id)
        s = Span(
            name=name,
            parent_id=_current_span.get(),
            trace_id=trace_id,
            start_ms=self._epoch + time.monotonic() * 1000.0,
            attrs=dict(attrs),
        )
        token = _current_span.set(s.span_id)
        t0 = time.monotonic()
        try:
            yield s
        except BaseException as exc:
            s.error = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            s.duration_ms = (time.monotonic() - t0) * 1000.0
            _current_span.reset(token)
            if trace_token is not None:
                _current_trace.reset(trace_token)
            with self._lock:
                self._spans.append(s)
                listeners = list(self._listeners)
            for fn in listeners:
                try:
                    fn(s)
                except Exception:  # noqa: BLE001 — tracing never throws
                    pass

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def recent(self, limit: int = 100, name: str | None = None) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return [s.to_dict() for s in spans[-limit:]]

    def for_trace(self, trace_id: str, limit: int = 1000) -> list[dict]:
        """This process's local fragment of one trace, oldest first —
        what `/trace?trace_id=` serves; stitch fragments from several
        nodes with `stitch_trace`."""
        with self._lock:
            spans = [s for s in self._spans if s.trace_id == trace_id]
        return [s.to_dict() for s in spans[-limit:]]

    def stats(self) -> dict[str, dict]:
        """Per-span-name aggregates: count, p50/p95/max duration, errors."""
        with self._lock:
            spans = list(self._spans)
            counters = dict(self.counters)
        by_name: dict[str, list[Span]] = {}
        for s in spans:
            by_name.setdefault(s.name, []).append(s)
        out: dict[str, dict] = {}
        for name, group in by_name.items():
            durs = sorted(s.duration_ms for s in group)
            out[name] = {
                "count": len(durs),
                "errors": sum(1 for s in group if s.error),
                "p50_ms": round(_pct(durs, 0.50), 3),
                "p95_ms": round(_pct(durs, 0.95), 3),
                "max_ms": round(durs[-1], 3),
            }
        if counters:
            out["_counters"] = counters
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.counters.clear()


def stitch_trace(
    fragments: list[dict], expected_nodes: list[str] | None = None
) -> dict:
    """Merge per-node trace fragments into one cross-node timeline.

    `fragments` is a list of ``{"node": <peer_id>, "spans": [span dicts]}``
    (each the payload of one node's ``/trace?trace_id=`` response). Spans
    are annotated with their node, de-duplicated by span_id (fragments may
    overlap when nodes share a process, e.g. loopback tests) and ordered
    by start_ms — parent links then read as one tree across nodes.

    Degrades gracefully instead of failing the whole stitch: a fragment
    marked ``{"unreachable": True}`` (the peer never answered) or
    ``{"partial": True}`` (it answered without a usable span list), and
    any ``expected_nodes`` entry that contributed no fragment, land in
    ``missing_peers`` and flip ``incomplete`` — the merged PARTIAL
    timeline is still returned."""
    seen: dict[str, dict] = {}
    responded: set = set()
    missing: set = set()
    for frag in fragments or []:
        node = frag.get("node")
        if frag.get("unreachable") or frag.get("partial"):
            if node:
                missing.add(node)
            continue
        if node:
            responded.add(node)
        for s in frag.get("spans") or []:
            sid = s.get("span_id")
            if sid is None or sid in seen:
                continue
            seen[sid] = {**s, "node": node}
    for node in expected_nodes or []:
        if node not in responded:
            missing.add(node)
    missing -= responded  # a duplicate fragment pair: any answer counts
    spans = sorted(seen.values(), key=lambda s: s.get("start_ms") or 0.0)
    trace_ids = {s.get("trace_id") for s in spans if s.get("trace_id")}
    return {
        "trace_id": next(iter(trace_ids)) if len(trace_ids) == 1 else None,
        "nodes": sorted({s["node"] for s in spans if s.get("node")}),
        "spans": spans,
        "incomplete": bool(missing),
        "missing_peers": sorted(missing),
    }


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


_GLOBAL = Tracer()


def get_tracer() -> Tracer:
    return _GLOBAL


@contextmanager
def device_profile(log_dir: str = "/tmp/bee2bee_trace"):
    """Capture an XLA device trace (TensorBoard `trace_viewer` readable).

    The TPU-native answer to "how do I see where the time goes": wraps
    jax.profiler.trace around any block — jit compiles, collectives, HBM
    transfers all appear in the timeline.
    """
    import jax

    with jax.profiler.trace(log_dir):
        with get_tracer().span("device_profile", log_dir=log_dir):
            yield log_dir


def annotate(name: str, **attrs):
    """jax.profiler.TraceAnnotation + host span in one: shows up both in
    the device timeline and in /trace output."""
    import jax

    @contextmanager
    def _cm():
        with jax.profiler.TraceAnnotation(name):
            with get_tracer().span(name, **attrs):
                yield

    return _cm()
