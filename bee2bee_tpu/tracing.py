"""Tracing and observability: request spans + on-demand device profiles.

The reference has NO tracing (SURVEY §5) — the closest artifacts are
per-request latency_ms (reference services.py:97-105) and ping RTTs
(reference p2p_runtime.py:544-557). This module is the required upgrade:

- `Tracer`: a lock-guarded ring buffer of completed `Span`s with nested
  span support (contextvar parent), percentile aggregation per span name,
  and zero dependencies. One process-global instance via `get_tracer()`.
- `Span` context manager works in sync and async code and never throws:
  tracing must not take down the serving path.
- `device_profile()`: wraps `jax.profiler.trace` so one call captures an
  XLA device trace viewable in TensorBoard/Perfetto.

Spans are cheap (monotonic clock + dict append) and bounded (ring
buffer), so they stay on in production; mesh nodes surface them at the
gateway's `/trace` route.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator

from .utils import new_id

_current_span: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "bee2bee_current_span", default=None
)


@dataclass
class Span:
    name: str
    span_id: str = field(default_factory=lambda: new_id("span"))
    parent_id: str | None = None
    start_ms: float = 0.0
    duration_ms: float = -1.0  # -1 while open
    attrs: dict[str, Any] = field(default_factory=dict)
    error: str | None = None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": round(self.duration_ms, 3),
            "attrs": self.attrs,
            "error": self.error,
        }


class Tracer:
    """Bounded in-memory span collector; thread-safe; never raises."""

    def __init__(self, capacity: int = 2048):
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._epoch = time.time() * 1000.0 - time.monotonic() * 1000.0
        self.counters: dict[str, int] = {}

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        s = Span(
            name=name,
            parent_id=_current_span.get(),
            start_ms=self._epoch + time.monotonic() * 1000.0,
            attrs=dict(attrs),
        )
        token = _current_span.set(s.span_id)
        t0 = time.monotonic()
        try:
            yield s
        except BaseException as exc:
            s.error = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            s.duration_ms = (time.monotonic() - t0) * 1000.0
            _current_span.reset(token)
            with self._lock:
                self._spans.append(s)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def recent(self, limit: int = 100, name: str | None = None) -> list[dict]:
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return [s.to_dict() for s in spans[-limit:]]

    def stats(self) -> dict[str, dict]:
        """Per-span-name aggregates: count, p50/p95/max duration, errors."""
        with self._lock:
            spans = list(self._spans)
            counters = dict(self.counters)
        by_name: dict[str, list[Span]] = {}
        for s in spans:
            by_name.setdefault(s.name, []).append(s)
        out: dict[str, dict] = {}
        for name, group in by_name.items():
            durs = sorted(s.duration_ms for s in group)
            out[name] = {
                "count": len(durs),
                "errors": sum(1 for s in group if s.error),
                "p50_ms": round(_pct(durs, 0.50), 3),
                "p95_ms": round(_pct(durs, 0.95), 3),
                "max_ms": round(durs[-1], 3),
            }
        if counters:
            out["_counters"] = counters
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.counters.clear()


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


_GLOBAL = Tracer()


def get_tracer() -> Tracer:
    return _GLOBAL


@contextmanager
def device_profile(log_dir: str = "/tmp/bee2bee_trace"):
    """Capture an XLA device trace (TensorBoard `trace_viewer` readable).

    The TPU-native answer to "how do I see where the time goes": wraps
    jax.profiler.trace around any block — jit compiles, collectives, HBM
    transfers all appear in the timeline.
    """
    import jax

    with jax.profiler.trace(log_dir):
        with get_tracer().span("device_profile", log_dir=log_dir):
            yield log_dir


def annotate(name: str, **attrs):
    """jax.profiler.TraceAnnotation + host span in one: shows up both in
    the device timeline and in /trace output."""
    import jax

    @contextmanager
    def _cm():
        with jax.profiler.TraceAnnotation(name):
            with get_tracer().span(name, **attrs):
                yield

    return _cm()
