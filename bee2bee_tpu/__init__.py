"""bee2bee-tpu: a TPU-native decentralized inference-serving mesh.

A brand-new framework with the capability contract of Chatit-cloud/BEE2BEE
(reference: /root/reference/bee2bee/__init__.py:1-11): peer-to-peer WebSocket
mesh nodes that host models, advertise them, and stream generations — but the
compute core is a jit-compiled JAX engine with a sharded KV cache on TPU, and
model parallelism (TP/PP/EP/SP) rides `jax.sharding` mesh axes instead of
per-layer JSON-over-WebSocket hops.

Heavy submodules (engine, models, mesh runtime) are imported lazily so that
`import bee2bee_tpu` stays cheap for CLI/metadata use.
"""

__version__ = "0.5.0"

_LAZY = {
    "P2PNode": ("bee2bee_tpu.meshnet.node", "P2PNode"),
    "run_p2p_node": ("bee2bee_tpu.meshnet.runtime", "run_p2p_node"),
    "InferenceEngine": ("bee2bee_tpu.engine.engine", "InferenceEngine"),
    "NodeClient": ("bee2bee_tpu.client", "NodeClient"),
    "GatewayClient": ("bee2bee_tpu.client", "GatewayClient"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        try:
            return getattr(importlib.import_module(module), attr)
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"{name} is not available in this build: {e}"
            ) from e
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "P2PNode",
    "run_p2p_node",
    "InferenceEngine",
    "NodeClient",
    "GatewayClient",
    "__version__",
]
