"""Pass family 6: raceguard (ML-R*) — async interleaving hazards.

Every interleaving bug that shipped in the mesh control plane (the
dual-dial half-deaf links, the mid-action epoch races) had the same
anatomy: a coroutine read shared state, awaited, and acted on the stale
read. This pass segments each ``async def`` at its await points and
flags the four shapes that anatomy takes:

- ML-R001 — check-then-act split across an await: an ``if`` whose test
  reads ``self.X``, whose guarded body awaits, and then mutates the same
  ``self.X`` without re-checking it. The await is a suspension point —
  any other coroutine can invalidate the check before the act lands.
  Re-checking the attribute after the await (or holding a lock around
  the whole check+act) clears the finding.
- ML-R002 — fire-and-forget task: a ``create_task``/``ensure_future``
  whose handle is dropped (bare statement, or bound to a name that is
  never read again). Exceptions in the task vanish, and asyncio keeps
  only a weak reference — GC can cancel the task mid-flight. Await it,
  route it through a tracked spawn helper (``utils.TaskTracker`` /
  ``node._spawn``), or attach a done-callback.
- ML-R003 — a shared container attribute mutated from 2+ distinct
  coroutine entry points (roots of the intra-class async call graph,
  plus dispatch-table handlers and spawned loops) with no guarding lock
  on any mutation path, at least one mutation landing after an await.
- ML-R004 — ``await`` inside iteration over a shared container
  (``for x in self.X``): mutation during the suspension invalidates the
  iterator (dict/set raise RuntimeError; lists silently skip). Snapshot
  first: ``for x in list(self.X.values())``.

The dynamic twin of this pass is the simnet interleaving fuzzer
(``bee2bee_tpu/simnet/fuzz.py``, docs/SIMULATION.md): what raceguard
flags statically, the fuzzer provokes by perturbing schedules.
"""

from __future__ import annotations

import ast

from .asyncsafe import _names_a_lock
from .core import dotted_name as _dotted

# spawn calls whose returned handle must not be dropped (ML-R002), matched
# by last dotted segment so loop.create_task / asyncio.ensure_future both hit
_SPAWN_CALLS = {"create_task", "ensure_future"}

# tracked-spawn wrappers: a self-method call inside their args is a new
# coroutine entry point for the ML-R003 call graph (a spawned loop)
_SPAWN_WRAPPERS = _SPAWN_CALLS | {"_spawn", "spawn"}

# method calls that mutate their receiver in place (container mutation)
_MUTATORS = {
    "add",
    "append",
    "appendleft",
    "clear",
    "discard",
    "extend",
    "insert",
    "pop",
    "popitem",
    "popleft",
    "remove",
    "setdefault",
    "update",
}


def _self_chain(expr: ast.AST) -> str:
    """Dotted chain for attribute expressions rooted at ``self`` ("" else)."""
    name = _dotted(expr)
    return name if name.startswith("self.") else ""


def _attrs_read(expr: ast.AST) -> frozenset:
    """Every ``self.…`` chain read anywhere in an expression (walking an
    attribute chain yields its prefixes too, so ``self.peers.get(pid)``
    credits both "self.peers.get" and "self.peers")."""
    attrs = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute):
            chain = _self_chain(n)
            if chain:
                attrs.add(chain)
    return frozenset(attrs)


def _holds_lock(node) -> bool:
    """Does this With/AsyncWith acquire something lock-shaped?"""
    return any(
        _names_a_lock(
            _dotted(
                item.context_expr.func
                if isinstance(item.context_expr, ast.Call)
                else item.context_expr
            )
        )
        for item in node.items
    )


# -------------------------------------------------- execution-order events
#
# A flat event stream over a statement list, in approximate execution
# order, skipping nested def/lambda/class bodies (they run off this
# coroutine's await flow). Events:
#   ("await", None, node)   — a suspension point
#   ("check", attrs, node)  — an If/While test reading self attrs
#   ("write", attr, node)   — a mutation of self.<attr> (rebind, subscript
#                             store/delete, or in-place mutator call)


def _stmt_events(stmts):
    for s in stmts:
        yield from _node_events(s)


def _node_events(node):
    if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
    ):
        return
    if isinstance(node, ast.Assign):
        # value evaluates before the store lands
        yield from _node_events(node.value)
        for t in node.targets:
            yield from _target_events(t)
        return
    if isinstance(node, ast.AugAssign):
        yield from _node_events(node.value)
        yield from _target_events(node.target)
        return
    if isinstance(node, ast.AnnAssign):
        if node.value is not None:
            yield from _node_events(node.value)
            yield from _target_events(node.target)
        return
    if isinstance(node, ast.Delete):
        for t in node.targets:
            yield from _target_events(t)
        return
    if isinstance(node, (ast.If, ast.While)):
        yield from _node_events(node.test)
        attrs = _attrs_read(node.test)
        if attrs:
            yield ("check", attrs, node)
        yield from _stmt_events(node.body)
        yield from _stmt_events(node.orelse)
        return
    if isinstance(node, ast.Await):
        yield from _node_events(node.value)
        yield ("await", None, node)
        return
    if isinstance(node, ast.Call):
        for child in ast.iter_child_nodes(node):
            yield from _node_events(child)
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            chain = _self_chain(node.func.value)
            if chain:
                yield ("write", chain, node)
        return
    for child in ast.iter_child_nodes(node):
        yield from _node_events(child)


def _target_events(t):
    if isinstance(t, ast.Attribute):
        chain = _self_chain(t)
        if chain:
            yield ("write", chain, t)
    elif isinstance(t, ast.Subscript):
        chain = _self_chain(t.value)
        if chain:
            yield ("write", chain, t)
        yield from _node_events(t.slice)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_events(e)
    elif isinstance(t, ast.Starred):
        yield from _target_events(t.value)


def _container_writes(events):
    """The subset of write events that are container mutations (subscript
    store/delete or in-place mutator call) — a plain attribute rebind is
    not a container mutation."""
    for kind, attr, node in events:
        if kind != "write":
            continue
        if isinstance(node, ast.Attribute):
            continue  # rebind: ML-R001's business, not ML-R003's
        yield attr, node


class RaceGuardPass:
    family = "race"
    rules = {
        "ML-R001": "check-then-act on shared state split across an await",
        "ML-R002": "fire-and-forget task: create_task handle dropped",
        "ML-R003": (
            "shared container mutated from multiple coroutine entry points "
            "without a lock"
        ),
        "ML-R004": "await inside iteration over a shared container",
    }

    def applies(self, path: str) -> bool:
        return path == "api.py" or path.startswith(
            ("meshnet/", "router/", "fleet/", "web/", "simnet/", "services/")
        )

    def run(self, ctx) -> list:
        findings: list = []
        parents = {
            child: parent
            for parent in ast.walk(ctx.tree)
            for child in ast.iter_child_nodes(parent)
        }
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._scan_toctou(ctx, node, findings)
                self._scan_iteration(ctx, node, findings)
            elif isinstance(node, ast.ClassDef):
                self._scan_entry_points(ctx, node, findings)
        self._scan_dropped_handles(ctx, parents, findings)
        return findings

    # ------------------------------------------------------------- ML-R001

    def _scan_toctou(self, ctx, fn, findings):
        for stmt, in_lock in _walk_with_lock(fn.body, False):
            if not isinstance(stmt, ast.If) or in_lock:
                continue
            guards = _attrs_read(stmt.test)
            if not guards:
                continue
            awaited = False
            pending = set(guards)
            for kind, attr, node in _stmt_events(stmt.body):
                if kind == "await":
                    awaited = True
                elif kind == "check" and awaited:
                    pending -= attr  # re-validated after the suspension
                elif kind == "write" and awaited and attr in pending:
                    pending.discard(attr)
                    findings.append(
                        ctx.finding(
                            "ML-R001",
                            node,
                            f"{attr} checked at line {stmt.lineno}, then "
                            "mutated after an await without re-checking",
                            "the await is a suspension point — another "
                            "coroutine can invalidate the check before the "
                            "act lands; re-check after the await or hold a "
                            "lock around check+act",
                        )
                    )

    # ------------------------------------------------------------- ML-R002

    def _scan_dropped_handles(self, ctx, parents, findings):
        for node in ast.walk(ctx.tree):
            call = None
            if isinstance(node, ast.Expr):
                call = node.value
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                call = node.value
            if not (
                isinstance(call, ast.Call)
                and _dotted(call.func).rsplit(".", 1)[-1] in _SPAWN_CALLS
            ):
                continue
            if isinstance(node, ast.Expr):
                self._r002(ctx, call, findings, "not stored anywhere")
                continue
            target = node.targets[0]
            if isinstance(target, ast.Name):
                scope = _enclosing(
                    node, parents, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) or ctx.tree
                if not _name_loaded(scope, target.id):
                    self._r002(
                        ctx, call, findings,
                        f"bound to `{target.id}` which is never read",
                    )
            elif isinstance(target, ast.Attribute):
                chain = _self_chain(target)
                if not chain:
                    continue
                scope = _enclosing(node, parents, (ast.ClassDef,)) or ctx.tree
                if not _attr_loaded(scope, chain):
                    self._r002(
                        ctx, call, findings,
                        f"bound to `{chain}` which is never read",
                    )

    def _r002(self, ctx, call, findings, how):
        findings.append(
            ctx.finding(
                "ML-R002",
                call,
                f"task handle from {_dotted(call.func)}(...) is dropped "
                f"({how})",
                "exceptions in the task vanish and asyncio's weak ref lets "
                "GC cancel it mid-flight — await it, route it through a "
                "tracked spawn helper (utils.TaskTracker / node._spawn), or "
                "attach a done-callback",
            )
        )

    # ------------------------------------------------------------- ML-R003

    def _scan_entry_points(self, ctx, cls, findings):
        methods = {
            m.name: m for m in cls.body if isinstance(m, ast.AsyncFunctionDef)
        }
        if len(methods) < 2:
            return
        mutations = {}  # attr -> list[(method, post_await, in_lock, node)]
        edges = {name: set() for name in methods}
        forced_roots = set()
        called = set()
        for name, m in methods.items():
            awaited = False
            for stmt, in_lock in _walk_with_lock(m.body, False):
                for kind, attr, node in _node_own_events(stmt):
                    if kind == "await":
                        awaited = True
                    elif kind == "write" and not isinstance(node, ast.Attribute):
                        mutations.setdefault(attr, []).append(
                            (name, awaited, in_lock, node)
                        )
                # intra-class call edges + spawned-loop roots
                if isinstance(stmt, ast.Call):
                    wrapper = (
                        _dotted(stmt.func).rsplit(".", 1)[-1] in _SPAWN_WRAPPERS
                    )
                    for arg in ast.walk(stmt):
                        if arg is stmt or not isinstance(arg, ast.Call):
                            continue
                        callee = self._self_method(arg, methods)
                        if callee and wrapper:
                            forced_roots.add(callee)
                    callee = self._self_method(stmt, methods)
                    if callee and not wrapper:
                        edges[name].add(callee)
                        called.add(callee)
        roots = (
            {n for n in methods if n not in called}
            | forced_roots
            | {n for n in methods if n.startswith("_handle_")}
        )
        reach = {r: _reachable(r, edges) for r in roots}
        for attr, sites in sorted(mutations.items()):
            if any(in_lock for _, _, in_lock, _ in sites):
                continue  # some path locks: lock discipline exists
            writers = {m for m, _, _, _ in sites}
            covering = sorted(r for r in roots if reach[r] & writers)
            post = [s for s in sites if s[1]]
            if len(covering) < 2 or not post:
                continue
            _, _, _, node = post[0]
            findings.append(
                ctx.finding(
                    "ML-R003",
                    node,
                    f"{attr} mutated from {len(covering)} coroutine entry "
                    f"points ({', '.join(covering)}) with no lock on any "
                    "path",
                    "concurrent entry points interleave at every await — "
                    "guard the mutations with one asyncio.Lock or funnel "
                    "them through a single owner task",
                )
            )

    @staticmethod
    def _self_method(call, methods):
        name = _dotted(call.func)
        if name.startswith("self.") and name.count(".") == 1:
            attr = name.split(".", 1)[1]
            if attr in methods:
                return attr
        return None

    # ------------------------------------------------------------- ML-R004

    def _scan_iteration(self, ctx, fn, findings):
        for stmt, in_lock in _walk_with_lock(fn.body, False):
            if not isinstance(stmt, ast.For) or in_lock:
                continue
            chain = self._shared_iter(stmt.iter)
            if not chain:
                continue
            if any(True for k, _, _ in _stmt_events(stmt.body) if k == "await"):
                findings.append(
                    ctx.finding(
                        "ML-R004",
                        stmt,
                        f"await inside iteration over shared container "
                        f"{chain}",
                        "a coroutine scheduled during the await can mutate "
                        f"{chain} and invalidate the iterator — snapshot "
                        f"first: `for … in list({chain}…)`",
                    )
                )

    @staticmethod
    def _shared_iter(it):
        if isinstance(it, ast.Attribute):
            return _self_chain(it)
        if (
            isinstance(it, ast.Call)
            and not it.args
            and isinstance(it.func, ast.Attribute)
            and it.func.attr in ("items", "values", "keys")
        ):
            return _self_chain(it.func.value)
        return ""


# ----------------------------------------------------------------- helpers


def _walk_with_lock(body, in_lock):
    """Yield (node, lock_held) over a statement subtree in source order,
    skipping nested def/lambda/class bodies, tracking With/AsyncWith lock
    acquisition the same way asyncsafe does."""
    for node in body:
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
        ):
            continue
        yield node, in_lock
        if isinstance(node, (ast.With, ast.AsyncWith)):
            holds = in_lock or _holds_lock(node)
            yield from _walk_with_lock(node.body, holds)
            continue
        children = [
            c
            for c in ast.iter_child_nodes(node)
            if not isinstance(c, (ast.expr_context, ast.operator))
        ]
        yield from _walk_with_lock(children, in_lock)


def _node_own_events(stmt):
    """Events contributed by this node itself (not statement children —
    _walk_with_lock already visits those), so compound statements don't
    double-count their bodies."""
    if isinstance(stmt, ast.Await):
        yield ("await", None, stmt)
    elif isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            yield from _target_events_shallow(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if not (isinstance(stmt, ast.AnnAssign) and stmt.value is None):
            yield from _target_events_shallow(stmt.target)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            yield from _target_events_shallow(t)
    elif isinstance(stmt, ast.Call):
        if isinstance(stmt.func, ast.Attribute) and stmt.func.attr in _MUTATORS:
            chain = _self_chain(stmt.func.value)
            if chain:
                yield ("write", chain, stmt)


def _target_events_shallow(t):
    if isinstance(t, ast.Attribute):
        chain = _self_chain(t)
        if chain:
            yield ("write", chain, t)
    elif isinstance(t, ast.Subscript):
        chain = _self_chain(t.value)
        if chain:
            yield ("write", chain, t)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_events_shallow(e)
    elif isinstance(t, ast.Starred):
        yield from _target_events_shallow(t.value)


def _reachable(root, edges):
    seen = {root}
    stack = [root]
    while stack:
        for callee in edges.get(stack.pop(), ()):
            if callee not in seen:
                seen.add(callee)
                stack.append(callee)
    return seen


def _enclosing(node, parents, types):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, types):
            return cur
        cur = parents.get(cur)
    return None


def _name_loaded(scope, name) -> bool:
    return any(
        isinstance(n, ast.Name)
        and n.id == name
        and isinstance(n.ctx, ast.Load)
        for n in ast.walk(scope)
    )


def _attr_loaded(scope, chain) -> bool:
    return any(
        isinstance(n, ast.Attribute)
        and isinstance(n.ctx, ast.Load)
        and _dotted(n) == chain
        for n in ast.walk(scope)
    )
