"""meshlint core: findings, suppressions, the ratchet baseline, the runner.

Conventions this analyzer turns into machine-checked invariants (see
docs/ANALYSIS.md for the full rule catalog):

- frames (ML-F*): wire frames must match the schema registry
  (analysis/schema.py) — the mesh silently ignores unknown keys, so a
  typo'd key is a silently-wrong output, not an error.
- async-safety (ML-A*): one blocking call inside the meshnet/gateway event
  loop stalls every in-flight generation.
- jax hygiene (ML-J*): a host sync inside a jit hot path erases the
  paged-cache/scheduler wins with an invisible device round trip.

The gate is **ratchet-only**: pre-existing findings are grandfathered in a
checked-in baseline (analysis/baseline.json) matched by (rule, path,
source-line snippet) — line numbers may drift, the offending line may not.
New findings fail `python -m bee2bee_tpu.analysis` and the tier-1 test
(tests/test_meshlint.py). Deliberate violations carry an inline
``# meshlint: ignore[rule-id] -- reason`` (the reason is required).
"""

from __future__ import annotations

import ast
import json
import re
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path

PACKAGE_ROOT = Path(__file__).resolve().parent.parent  # bee2bee_tpu/
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# rule id for a suppression comment with no reason — an unexplained ignore
# is itself a finding, so suppressions stay auditable
BAD_SUPPRESSION = "ML-S001"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # package-relative, e.g. "meshnet/node.py"
    line: int
    col: int
    message: str
    hint: str = ""
    snippet: str = ""  # stripped source line — the baseline fingerprint

    def key(self) -> tuple:
        return (self.rule, self.path, self.snippet)

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "snippet": self.snippet,
        }


@dataclass
class FileContext:
    """Everything a pass needs about one source file."""

    path: str  # virtual (package-relative) path used for scoping/reporting
    src: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(
        self, rule: str, node: ast.AST, message: str, hint: str = ""
    ) -> Finding:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule, self.path, line, col, message, hint, self.snippet(line)
        )


def dotted_name(expr: ast.AST) -> str:
    """AST expression → dotted call-target name ("time.sleep" for
    ``time.sleep(...)``, "span" for ``get_tracer().span`` — the chain
    stops at any non-Name base). Shared by the passes so name resolution
    can't diverge between them."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    return ".".join(reversed(parts))


# ------------------------------------------------------------- suppressions

# `# meshlint: ignore[ML-F001]` or `ignore[ML-F001,ML-A003]` or `ignore[*]`,
# followed by a REQUIRED free-text reason (optionally after --/:/ dashes)
_SUPPRESS_RE = re.compile(
    r"#\s*meshlint:\s*ignore\[([A-Za-z0-9_*,\- ]+)\]\s*(?:[-—:]*\s*)?(.*)"
)


@dataclass(frozen=True)
class Suppression:
    line: int
    rules: frozenset
    reason: str

    def covers(self, finding: Finding) -> bool:
        return finding.line == self.line and (
            "*" in self.rules or finding.rule in self.rules
        )


def parse_suppressions(ctx: FileContext) -> tuple[list[Suppression], list[Finding]]:
    """Inline suppressions + findings for suppressions missing a reason."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    for i, text in enumerate(ctx.lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = m.group(2).strip()
        if not reason:
            bad.append(
                Finding(
                    BAD_SUPPRESSION,
                    ctx.path,
                    i,
                    text.index("#"),
                    "meshlint suppression without a reason",
                    "write `# meshlint: ignore[rule] -- why this is safe`",
                    text.strip(),
                )
            )
            continue
        sups.append(Suppression(i, rules, reason))
    return sups, bad


# ----------------------------------------------------------------- baseline


def load_baseline(path: str | Path | None = None) -> Counter:
    """Baseline as a multiset of (rule, path, snippet) fingerprints."""
    p = Path(path) if path else DEFAULT_BASELINE
    if not p.exists():
        return Counter()
    data = json.loads(p.read_text())
    return Counter(
        (f["rule"], f["path"], f.get("snippet", "")) for f in data.get("findings", [])
    )


def write_baseline(findings: list[Finding], path: str | Path | None = None) -> Path:
    p = Path(path) if path else DEFAULT_BASELINE
    payload = {
        "version": 1,
        "comment": (
            "meshlint ratchet baseline: grandfathered findings matched by "
            "(rule, path, snippet). Regenerate with "
            "`python -m bee2bee_tpu.analysis --write-baseline` — only ever "
            "to REMOVE entries you fixed; new code must ship clean."
        ),
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line, "snippet": f.snippet}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    p.write_text(json.dumps(payload, indent=2) + "\n")
    return p


def filter_baselined(
    findings: list[Finding], baseline: Counter
) -> tuple[list[Finding], list[Finding]]:
    """(new, grandfathered). Matching consumes baseline entries so N
    baselined occurrences never absorb N+1 findings of the same shape."""
    budget = Counter(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# ------------------------------------------------------------------- runner


def _passes():
    # imported lazily: the pass modules import this one for Finding/ctx
    from .asyncsafe import AsyncSafetyPass
    from .clockseam import ClockSeamPass
    from .frames import FramesPass
    from .jaxhygiene import JaxHygienePass
    from .raceguard import RaceGuardPass
    from .telemetry import TelemetryPass

    return (
        FramesPass(),
        AsyncSafetyPass(),
        JaxHygienePass(),
        TelemetryPass(),
        ClockSeamPass(),
        RaceGuardPass(),
    )


def rule_catalog() -> dict[str, str]:
    cat = {BAD_SUPPRESSION: "meshlint suppression without a reason"}
    for p in _passes():
        cat.update(p.rules)
    return cat


# subdirectories of the package: out-of-tree checkouts/copies scope by
# these names so `python -m bee2bee_tpu.analysis /elsewhere/meshnet/x.py`
# still runs the right passes (a basename-only fallback would silently
# skip the frames/jax rules on anything outside the installed package)
_PACKAGE_DIRS = frozenset(
    {
        "analysis",
        "engine",
        "fleet",
        "meshnet",
        "models",
        "ops",
        "parallel",
        "router",
        "services",
        "simnet",
        "train",
        "web",
    }
)


def virtual_path(path: str | Path) -> str:
    """Package-relative posix path ("meshnet/node.py") used for pass
    scoping and baseline fingerprints. Files outside the installed
    package scope by their rightmost `bee2bee_tpu/` component or by a
    recognizable package subdirectory; anything else keeps its name (the
    self-test fixtures pass an explicit virtual path instead)."""
    p = Path(path).resolve()
    try:
        return p.relative_to(PACKAGE_ROOT).as_posix()
    except ValueError:
        pass
    parts = p.parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == "bee2bee_tpu" and i + 1 < len(parts):
            return "/".join(parts[i + 1:])
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] in _PACKAGE_DIRS:
            return "/".join(parts[i:])
    return p.name


def analyze_source(
    src: str,
    path: str,
    families: frozenset | None = None,
) -> list[Finding]:
    """Run the passes over one source string. `path` is the VIRTUAL path —
    it selects which pass families apply (e.g. "meshnet/x.py" gets the
    frames + async rules; "engine/x.py" gets jax hygiene)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [
            Finding(
                "ML-E000",
                path,
                e.lineno or 0,
                e.offset or 0,
                f"syntax error: {e.msg}",
                snippet="",
            )
        ]
    ctx = FileContext(path=path, src=src, tree=tree, lines=src.splitlines())
    sups, findings = parse_suppressions(ctx)
    for p in _passes():
        if families is not None and p.family not in families:
            continue
        if not p.applies(path):
            continue
        findings.extend(p.run(ctx))
    findings = [f for f in findings if not any(s.covers(f) for s in sups)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_py_files(root: str | Path) -> list[Path]:
    root = Path(root)
    if root.is_file():
        return [root]
    return sorted(
        p
        for p in root.rglob("*.py")
        if "__pycache__" not in p.parts and "static" not in p.parts
    )


def analyze_paths(
    paths: list[str | Path],
    families: frozenset | None = None,
) -> list[Finding]:
    findings: list[Finding] = []
    for root in paths:
        for f in iter_py_files(root):
            findings.extend(
                analyze_source(
                    f.read_text(encoding="utf-8"), virtual_path(f), families
                )
            )
    return findings
