"""Pass family 3: JAX hygiene (ML-J*).

The engine's throughput rests on jit hot paths staying on-device: one
implicit host sync per decode step erases the paged-cache and batching
wins with a device→host round trip the profiler shows only as "gap".
Rules, applied to jit-compiled functions in engine/, models/, ops/,
parallel/:

- ML-J001 — implicit host sync inside a jit-reachable function:
  ``.item()`` / ``.tolist()`` / ``.block_until_ready()``, ``np.asarray``/
  ``np.array``/``np.frombuffer`` on the numpy (not jax.numpy) alias, or a
  ``float()``/``int()``/``bool()`` cast of a function parameter (traced
  values fail or sync there; static config belongs in static_argnums).
- ML-J002 — Python branching on a traced value: an ``if``/``while`` test
  built from ``jnp.*``/``jax.lax``/``lax.*`` calls raises
  TracerBoolConversionError at trace time or, worse, burns the first
  trace's branch into the compiled graph. Use ``jnp.where`` /
  ``lax.cond``.
- ML-J003 — host sync inside the scheduler's decode hot-loop region:
  ``.item()``/``.tolist()``/``.block_until_ready()``, ``np.asarray``/
  ``np.array`` on the numpy alias, or ``jax.device_get`` lexically inside
  the step-loop methods (engine/scheduler.py ``_step`` and the window
  helpers it drives). The overlap design (docs/PERF.md "Decode hot
  loop") permits exactly ONE host sync per readback window — the token
  fetch in ``_fetch_window`` / the verdict fetch in ``_spec_step``,
  each carrying a same-line suppression naming itself. Any other sync
  in the region serializes the device behind host work the async ring
  exists to overlap, and every occurrence must argue its case in a
  suppression reason.

"jit-reachable" is resolved statically: functions decorated with
``@jax.jit`` (directly or via partial), functions/methods wrapped as
``x = jax.jit(fn)``, lambdas inside ``jax.jit(...)``, bodies passed
to ``jax.lax.scan/cond/while_loop/fori_loop/switch``, shard_map bodies,
and pallas kernel bodies passed to ``pl.pallas_call(kernel, ...)``
(directly or via partial) — a host sync inside a pallas kernel fails to
lower on real TPU and silently de-optimizes interpret mode.
"""

from __future__ import annotations

import ast

from .core import dotted_name as _dotted

_SCOPES = ("engine/", "models/", "ops/", "parallel/")
_HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_NP_HOST_FNS = {"asarray", "array", "frombuffer", "copy"}
_LAX_WRAPPERS = {"scan", "cond", "while_loop", "fori_loop", "switch"}
_CAST_NAMES = {"float", "int", "bool"}
# the decode hot-loop region (ML-J003): the scheduler step loop and the
# window helpers it drives every readback. Matched by METHOD NAME within
# engine/ files — the region is a contract on these names, so a renamed
# helper must update this set (the known-bad fixture in test_meshlint
# pins the coverage)
_HOT_LOOP_FNS = {
    "_step",
    "_spec_step",
    "_dispatch_window",
    "_overlap_ready",
    "_fetch_window",
    "_process_window",
    "_drain_inflight",
    "_process_row_tokens",
}


class _Aliases:
    def __init__(self, tree: ast.AST):
        self.numpy: set[str] = set()
        self.jnp: set[str] = set()
        self.lax: set[str] = set()
        self.jit_names: set[str] = {"jax.jit"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.numpy.add(bound)
                    elif a.name == "jax.numpy":
                        self.jnp.add(a.asname or "jax.numpy")
                    elif a.name == "jax.lax":
                        self.lax.add(a.asname or "jax.lax")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    if mod == "jax" and a.name == "jit":
                        self.jit_names.add(a.asname or "jit")
                    elif mod == "jax" and a.name == "lax":
                        self.lax.add(a.asname or "lax")
                    elif mod == "jax" and a.name == "numpy":
                        self.jnp.add(a.asname or "numpy")

    def is_jit(self, name: str) -> bool:
        return name in self.jit_names

    def is_traced_ns(self, name: str) -> bool:
        """dotted call base that yields traced arrays (jnp.*, lax.*)."""
        base = name.rsplit(".", 1)[0] if "." in name else ""
        return base in self.jnp or base in self.lax or base in ("jax.lax", "jax.numpy")


class JaxHygienePass:
    family = "jax"
    rules = {
        "ML-J001": "implicit host sync inside a jit-compiled function",
        "ML-J002": "Python branch on a traced value inside jit",
        "ML-J003": "host sync inside the scheduler's decode hot-loop region",
    }

    def applies(self, path: str) -> bool:
        return path.startswith(_SCOPES)

    def run(self, ctx) -> list:
        al = _Aliases(ctx.tree)
        roots = self._collect_jit_roots(ctx.tree, al)
        findings: list = []
        seen: set[int] = set()
        for fn in roots:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            params = self._params(fn)
            for node in ast.walk(fn):
                self._check(ctx, node, al, params, findings)
        if ctx.path.startswith("engine/"):
            for fn in ast.walk(ctx.tree):
                if (
                    isinstance(fn, ast.FunctionDef)
                    and fn.name in _HOT_LOOP_FNS
                    and id(fn) not in seen  # a jit root got ML-J001 already
                ):
                    for node in ast.walk(fn):
                        self._check_hot_loop(ctx, node, al, findings)
        return findings

    # -------------------------------------------------------------- roots

    def _params(self, fn) -> set[str]:
        a = fn.args  # FunctionDef and Lambda share the arguments layout
        names = {x.arg for x in list(a.args) + list(a.kwonlyargs) + list(a.posonlyargs)}
        names.discard("self")
        return names

    def _collect_jit_roots(self, tree: ast.AST, al: _Aliases) -> list:
        by_name: dict[str, list] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        # `kernel = functools.partial(_kernel, ...)` then
        # `pl.pallas_call(kernel, ...)` — the ops/ kernel wiring binds the
        # partial to a local first, so follow Name→partial hops. Keyed by
        # bare name across the file, so two functions reusing the same
        # local name collide: keep EVERY binding and mark them all — an
        # over-approximation scans extra functions, never misses one.
        partial_bindings: dict[str, list] = {}
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _dotted(node.value.func) in ("partial", "functools.partial")
                and node.value.args
            ):
                partial_bindings.setdefault(node.targets[0].id, []).append(
                    node.value.args[0]
                )
        roots: list = []
        visited_bindings: set[int] = set()  # no revisit loop on cycles

        def mark(expr: ast.AST):
            if isinstance(expr, ast.Lambda):
                roots.append(expr)
            elif isinstance(expr, ast.Name):
                roots.extend(by_name.get(expr.id, ()))
                for bound in partial_bindings.get(expr.id, ()):
                    if id(bound) not in visited_bindings:
                        visited_bindings.add(id(bound))
                        mark(bound)
            elif isinstance(expr, ast.Attribute):  # self._decode_fn
                roots.extend(by_name.get(expr.attr, ()))
            elif isinstance(expr, ast.Call) and expr.args and _dotted(
                expr.func
            ) in ("partial", "functools.partial"):
                mark(expr.args[0])  # shard_map(partial(body, ...), ...)

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    name = _dotted(dec)
                    if al.is_jit(name):
                        roots.append(node)
                    elif isinstance(dec, ast.Call):
                        cname = _dotted(dec.func)
                        if al.is_jit(cname):
                            roots.append(node)
                        elif cname in ("partial", "functools.partial") and dec.args:
                            if al.is_jit(_dotted(dec.args[0])):
                                roots.append(node)
            elif isinstance(node, ast.Call):
                name = _dotted(node.func)
                if al.is_jit(name) and node.args:
                    mark(node.args[0])
                elif name.rsplit(".", 1)[-1] == "shard_map" and node.args:
                    # SPMD bodies are traced exactly like jit bodies (the
                    # compat shim resolves to jax's shard_map either way)
                    mark(node.args[0])
                elif name.rsplit(".", 1)[-1] == "pallas_call" and node.args:
                    # pallas kernels (ops/flash.py, ops/ragged.py) are
                    # traced into Mosaic: host syncs / Python branches on
                    # traced values fail to lower on real TPU — the kernel
                    # body (often functools.partial(kernel, ...)) is a root
                    mark(node.args[0])
                elif (
                    name.rsplit(".", 1)[-1] in _LAX_WRAPPERS
                    and al.is_traced_ns(name)
                    and node.args
                ):
                    # scan(body, ...) / cond(pred, true_fn, false_fn, ...)
                    for arg in node.args[: 3 if name.endswith("cond") else 1]:
                        mark(arg)
        return roots

    # ------------------------------------------------------------- checks

    def _check(self, ctx, node, al: _Aliases, params: set, findings: list):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            last = name.rsplit(".", 1)[-1]
            if isinstance(node.func, ast.Attribute) and last in _HOST_SYNC_ATTRS:
                findings.append(
                    ctx.finding(
                        "ML-J001",
                        node,
                        f".{last}() inside a jit-compiled function",
                        "forces a device→host sync (or fails under trace) — "
                        "keep the value on-device or move the sync outside jit",
                    )
                )
            elif (
                "." in name
                and name.rsplit(".", 1)[0] in al.numpy
                and last in _NP_HOST_FNS
            ):
                findings.append(
                    ctx.finding(
                        "ML-J001",
                        node,
                        f"{name}() materializes a host array inside jit",
                        "use jnp.* on-device; np.* forces a transfer per call",
                    )
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in _CAST_NAMES
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in params
            ):
                findings.append(
                    ctx.finding(
                        "ML-J001",
                        node,
                        f"{node.func.id}() cast of parameter "
                        f"{node.args[0].id!r} inside jit",
                        "a traced argument syncs (or raises) here — mark it "
                        "static_argnums or keep it an array",
                    )
                )
        elif isinstance(node, (ast.If, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call) and al.is_traced_ns(_dotted(sub.func)):
                    findings.append(
                        ctx.finding(
                            "ML-J002",
                            node,
                            "Python branch on a traced expression inside jit",
                            "trace-time TracerBoolConversionError (or a "
                            "burned-in branch) — use jnp.where / lax.cond",
                        )
                    )
                    break

    def _check_hot_loop(self, ctx, node, al: _Aliases, findings: list):
        """ML-J003: the decode hot loop's sync budget is ONE fetch per
        readback window. Every .item()/.tolist()/.block_until_ready(),
        numpy-alias materialization, or jax.device_get in the region is a
        finding — the sanctioned fetches carry same-line suppressions
        whose reasons name the contract."""
        if not isinstance(node, ast.Call):
            return
        name = _dotted(node.func)
        last = name.rsplit(".", 1)[-1]
        if isinstance(node.func, ast.Attribute) and last in _HOST_SYNC_ATTRS:
            findings.append(
                ctx.finding(
                    "ML-J003",
                    node,
                    f".{last}() inside the decode hot-loop region",
                    "blocks the step loop on a device→host sync the "
                    "readback ring did not schedule — batch it into the "
                    "window fetch or move it off the hot path",
                )
            )
        elif (
            "." in name
            and name.rsplit(".", 1)[0] in al.numpy
            and last in _NP_HOST_FNS
        ):
            findings.append(
                ctx.finding(
                    "ML-J003",
                    node,
                    f"{name}() in the decode hot-loop region",
                    "materializing a device value here serializes the "
                    "device behind host work — only the per-window token "
                    "fetch may sync (suppress with the contract's reason)",
                )
            )
        elif last == "device_get":
            findings.append(
                ctx.finding(
                    "ML-J003",
                    node,
                    "jax.device_get() in the decode hot-loop region",
                    "an unscheduled host sync in the step loop — the "
                    "overlap design permits one fetch per readback window "
                    "(suppress with the contract's reason)",
                )
            )
