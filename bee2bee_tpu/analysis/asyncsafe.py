"""Pass family 2: async-safety (ML-A*).

~2.5k lines of meshnet/failover code run on one asyncio loop; a single
blocking call there stalls every in-flight generation on the node. Rules:

- ML-A001 — blocking call (time.sleep, requests.*, urllib urlopen, socket
  connect, subprocess, os.system, builtin open) directly inside an
  ``async def`` body. Offload via ``asyncio.to_thread`` /
  ``run_in_executor`` (nested sync ``def``/``lambda`` bodies are exempt —
  they already run off-loop when dispatched correctly).
- ML-A002 — unbounded network await on meshnet/web hot paths: bare
  ``await x.recv()`` and ``await websockets.connect(...)`` without an
  ``open_timeout``/``timeout``. Wrap in ``asyncio.wait_for`` or pass the
  timeout kwarg — a black-holed peer must not wedge the caller forever.
- ML-A003 — network await while holding an ``asyncio.Lock`` (an
  ``async with ...lock:`` block): one slow peer send serializes every
  other task contending for the lock. Snapshot under the lock, send
  outside it (the pattern node.py's broadcast uses).
"""

from __future__ import annotations

import ast

from .core import dotted_name as _dotted

# blocking targets by dotted name; "requests." matches the whole module
_BLOCKING_EXACT = {
    "time.sleep",
    "urllib.request.urlopen",
    "socket.create_connection",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "os.system",
    "open",
}
_BLOCKING_PREFIXES = ("requests.",)

# awaits that talk to the network: forbidden while a lock is held
_NETWORK_AWAITS = {
    "send",
    "_send",
    "recv",
    "connect",
    "_connect_peer",
    "broadcast",
    "request_generation",
    "run_stage_task",
}

_TIMEOUT_KWARGS = {"timeout", "open_timeout", "close_timeout"}


def _names_a_lock(dotted: str) -> bool:
    """Does a context-manager name look like a lock? Segment-wise match so
    the paged-cache vocabulary ("block_pool", "blocked", "unblock" — all
    containing the substring "lock") never trips ML-A003: only a segment
    that IS "lock"/"locked" or ends in "...lock" without being a
    "...block" counts (self._lock, pending_lock, rwlock)."""
    for seg in dotted.lower().replace(".", "_").split("_"):
        if seg in ("lock", "locked") or (
            seg.endswith("lock") and not seg.endswith("block")
        ):
            return True
    return False


def _websocket_aliases(tree: ast.AST) -> set[str]:
    """Names bound to the websockets module (including the wscompat shim
    imported `as websockets`)."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] == "websockets":
                    aliases.add(a.asname or "websockets")
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name in ("websockets", "wscompat"):
                    aliases.add(a.asname or a.name)
    return aliases


class AsyncSafetyPass:
    family = "async"
    rules = {
        "ML-A001": "blocking call inside async def",
        "ML-A002": "network await without a timeout on a mesh hot path",
        "ML-A003": "network await while holding an asyncio lock",
    }

    def applies(self, path: str) -> bool:
        return True  # any async def anywhere can stall its loop

    def run(self, ctx) -> list:
        findings: list = []
        hot_path = ctx.path.startswith(("meshnet/", "web/"))
        ws_aliases = _websocket_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._scan(ctx, node.body, findings, hot_path, ws_aliases, False)
        return findings

    # ------------------------------------------------------------- scanning

    def _scan(self, ctx, body, findings, hot_path, ws_aliases, in_lock):
        for stmt in body:
            self._scan_node(ctx, stmt, findings, hot_path, ws_aliases, in_lock)

    def _scan_node(self, ctx, node, findings, hot_path, ws_aliases, in_lock):
        # nested defs/lambdas run off this coroutine's await flow: their
        # bodies are not scanned here (nested async defs are scanned by
        # the top-level walk on their own)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, (ast.AsyncWith, ast.With)):
            holds = in_lock or any(
                _names_a_lock(
                    _dotted(item.context_expr.func
                            if isinstance(item.context_expr, ast.Call)
                            else item.context_expr)
                )
                for item in node.items
            )
            for item in node.items:
                self._scan_node(
                    ctx, item.context_expr, findings, hot_path, ws_aliases, in_lock
                )
            self._scan(ctx, node.body, findings, hot_path, ws_aliases, holds)
            return
        if isinstance(node, ast.Await):
            self._check_await(ctx, node, findings, hot_path, ws_aliases, in_lock)
        elif isinstance(node, ast.Call):
            self._check_blocking(ctx, node, findings)
        for child in ast.iter_child_nodes(node):
            self._scan_node(ctx, child, findings, hot_path, ws_aliases, in_lock)

    def _check_blocking(self, ctx, call: ast.Call, findings):
        name = _dotted(call.func)
        if name in _BLOCKING_EXACT or name.startswith(_BLOCKING_PREFIXES):
            findings.append(
                ctx.finding(
                    "ML-A001",
                    call,
                    f"blocking call {name}() inside async def",
                    "stalls every in-flight generation on this loop — "
                    "offload via asyncio.to_thread / run_in_executor",
                )
            )

    def _check_await(self, ctx, node: ast.Await, findings, hot_path, ws_aliases,
                     in_lock):
        call = node.value
        if not isinstance(call, ast.Call):
            return
        name = _dotted(call.func)
        last = name.rsplit(".", 1)[-1]
        if in_lock and last in _NETWORK_AWAITS:
            findings.append(
                ctx.finding(
                    "ML-A003",
                    node,
                    f"await {name}(...) while holding an asyncio lock",
                    "one slow peer serializes everyone contending for the "
                    "lock — snapshot under the lock, await outside it",
                )
            )
        if not hot_path:
            return
        if last == "recv" and not call.args:
            findings.append(
                ctx.finding(
                    "ML-A002",
                    node,
                    "bare await recv() with no timeout",
                    "a black-holed peer wedges this task forever — wrap in "
                    "asyncio.wait_for",
                )
            )
        elif (
            last == "connect"
            and name.rsplit(".", 1)[0] in ws_aliases
            and not any(
                kw.arg in _TIMEOUT_KWARGS for kw in call.keywords if kw.arg
            )
        ):
            findings.append(
                ctx.finding(
                    "ML-A002",
                    node,
                    "websocket connect without open_timeout",
                    "dialing a dead addr blocks until the OS gives up — "
                    "pass open_timeout=... or wrap in asyncio.wait_for",
                )
            )
