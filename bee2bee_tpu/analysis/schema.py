"""The checked frame-schema registry: which keys each wire message may carry.

This is the machine-checked half of the wire-compat contract that
``protocol.py`` can only state in comments: the reference mesh *silently
ignores unknown JSON keys* (protocol.py's SAMPLING_KEYS note), so a typo'd
key is not an error anywhere — it is a silently-wrong output at the far end.
The frames pass (analysis/frames.py) checks every frame construction and
every message-dict read in ``meshnet/``, ``web/``, ``services/`` and
``api.py`` against these schemas.

**Extending the protocol?** Add the new key here in the same change that
introduces it on the wire — `python -m bee2bee_tpu.analysis` (and the tier-1
gate tests/test_meshlint.py) fails otherwise. Op constants and SAMPLING_KEYS
are imported from ``protocol`` so the registry can never drift from the
constant set itself; only the per-op *key lists* live here.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import protocol as P

# reply-correlation id: the node answers either key (reference bridge sends
# task_id, our request path sends rid), so frames need ONE of them, not both
ID_KEYS = frozenset({"rid", "task_id"})

# cross-node trace propagation (tracing.TraceContext): optional on the
# frames a generation/task traverses so worker spans parent under the
# originating request — declared here so meshlint's reads/construction
# checks know the key (protocol.TRACE_CTX holds the wire name)
TRACE_KEYS = frozenset({P.TRACE_CTX})

# per-tenant identity (router/tenants.py): rides GEN_REQUEST api→node→relay
# so admission fairness bills the same tenant at every hop
TENANT_KEYS = frozenset({P.TENANT})

# multi-adapter serving (adapters/): the LoRA adapter a generation runs
# under rides GEN_REQUEST (clamped at the receiving node — unknown claims
# answer a typed unknown_adapter GEN_ERROR, never mint state)
ADAPTER_REQ_KEYS = frozenset({P.ADAPTER})

# typed admission rejections (router/admission.py): every 429/503 shed —
# HTTP response AND p2p GEN_ERROR frame alike — carries the rejection kind
# and the Retry-After hint, so callers can back off instead of hammering
ADMISSION_KEYS = frozenset({"error_kind", "retry_after_s"})

# the service result dict (services/base.py result_dict + streaming done
# line) rides gen_success / gen_result via `**result`
RESULT_FIELDS = frozenset(
    {
        "text",
        "tokens",
        "cost",
        "latency_ms",
        "price_per_token",
        "streamed",
        "backend",
        "finish_reason",
        "prompt_tokens",
        "partial",
        "via",
        "error",
        # per-request serving observability (ISSUE 5): TPUService attaches
        # them, the node/relay/gateway forward them verbatim
        "timing",
        "tokens_per_sec",
        "ttft_ms",
    }
)


@dataclass(frozen=True)
class FrameSchema:
    """Key contract for one message op ("type" is implicit on every frame)."""

    op: str
    required: frozenset = frozenset()
    optional: frozenset = frozenset()
    # groups of alternatives: at least one key of each group must be present
    required_any: tuple = ()
    # GEN_REQUEST-style frames additionally carry protocol.SAMPLING_KEYS
    allow_sampling: bool = False
    # reference-compat ops we never construct: reads allowed, keys unchecked
    allow_extra: bool = False

    def allowed_keys(self) -> frozenset:
        keys = self.required | self.optional | {"type"}
        for group in self.required_any:
            keys = keys | group
        if self.allow_sampling:
            keys = keys | frozenset(P.SAMPLING_KEYS)
        return keys


def _fs(*args, **kw) -> FrameSchema:
    return FrameSchema(*args, **kw)


FRAME_SCHEMAS: dict[str, FrameSchema] = {
    s.op: s
    for s in (
        _fs(
            P.HELLO,
            required=frozenset({"peer_id"}),
            optional=frozenset(
                {
                    "addr",
                    "region",
                    "metrics",
                    "services",
                    "api_port",
                    "api_host",
                    "accepts_stages",
                }
            ),
        ),
        _fs(P.PEER_LIST, required=frozenset({"peers"})),
        _fs(P.PING, required=frozenset({"ts"}), optional=frozenset({"metrics"})),
        _fs(P.PONG, required=frozenset({"ts"})),
        _fs(
            P.SERVICE_ANNOUNCE,
            required=frozenset({"service"}),
            optional=frozenset({"meta"}),
        ),
        _fs(
            P.GEN_REQUEST,
            required=frozenset({"prompt"}),
            required_any=(ID_KEYS,),
            optional=frozenset(
                {"model", "svc", "max_new_tokens", "max_tokens", "temperature", "stream"}
            )
            | TRACE_KEYS
            | TENANT_KEYS
            | ADAPTER_REQ_KEYS,
            allow_sampling=True,
        ),
        # `tokens`: migration resume streams (meshnet/migrate.py) carry the
        # accepted token IDS alongside the text so the source node can feed
        # its original Request's accounting exactly (text alone would force
        # a lossy re-tokenization at the bridge)
        _fs(
            P.GEN_CHUNK,
            required=frozenset({"text"}),
            required_any=(ID_KEYS,),
            optional=frozenset({"tokens"}),
        ),
        _fs(P.GEN_SUCCESS, required_any=(ID_KEYS,), optional=RESULT_FIELDS),
        _fs(
            P.GEN_ERROR,
            required=frozenset({"error"}),
            required_any=(ID_KEYS,),
            # typed admission rejections (429/503 over the wire)
            optional=ADMISSION_KEYS,
        ),
        # GEN_RESULT answers relays too: a relay target's typed admission
        # rejection forwards its error_kind/retry_after_s intact
        _fs(
            P.GEN_RESULT,
            required_any=(ID_KEYS,),
            optional=RESULT_FIELDS | ADMISSION_KEYS,
        ),
        _fs(P.PIECE_REQUEST, required=frozenset({"rid", "hash"})),
        _fs(
            P.PIECE_DATA,
            required=frozenset({"rid", "hash"}),
            optional=frozenset({"error"}),
        ),
        _fs(P.PIECE_HAVE, required=frozenset({"hashes"})),
        _fs(P.GOODBYE, required=frozenset({"peer_id"})),
        # health-plane gossip (health.build_digest rides the ping cadence);
        # the digest is ONE opaque dict on the wire — its internal layout
        # is versioned by health.DIGEST_VERSION, not by frame schema
        # (drain state and the disagg role ride INSIDE it as digest keys,
        # as does the observatory's trend block — digest["trend"], its
        # own layout versioned by obs.TREND_DIGEST_VERSION)
        _fs(P.TELEMETRY, required=frozenset({"peer_id", "digest"})),
        # live generation migration (meshnet/migrate.py). `gen` is the
        # generation snapshot (one opaque dict, layout versioned by its
        # own "v" key — engine/scheduler._snapshot_meta); `sig` the
        # source engine's pool-compat signature; `kv_chunks` how many
        # KV_BLOCKS frames follow (0 = re-prefill import, no KV ships);
        # `reason` the migration cause (drain/prefill_handoff/...).
        _fs(
            P.KV_EXPORT,
            required=frozenset({"rid", "model", "gen"}),
            optional=frozenset({"svc", "sig", "kv_chunks", "reason"})
            | TENANT_KEYS
            | TRACE_KEYS,
        ),
        # one chunk of exported pool blocks: binary tensor frame whose
        # header carries per-tensor sha256 (`hashes`, pieces.py-style) the
        # importer verifies before any block touches its pool
        _fs(
            P.KV_BLOCKS,
            required=frozenset({"rid", "seq"}),
            optional=frozenset({"done", "hashes"}),
        ),
        # the target's typed verdict: ok, or error + error_kind so the
        # source picks the right fallback rung (re-prefill vs typed fail)
        _fs(
            P.KV_IMPORT_ACK,
            required=frozenset({"rid"}),
            optional=frozenset({"ok"}) | ADMISSION_KEYS | frozenset({"error"}),
        ),
        # elastic fleet control (fleet/). FLEET_LEASE is the gossiped
        # controller lease: `holder` + monotonic `epoch` order claims
        # deterministically (higher epoch wins, ties break to the
        # lexicographically smaller holder id), `ttl_s` is relative so
        # receivers stamp arrival time instead of comparing clocks;
        # `action` is the leader's in-flight replica action (one opaque
        # descriptor — a successor adopts or rolls it back), `released`
        # zeroes the TTL on clean stepdown/shutdown.
        _fs(
            P.FLEET_LEASE,
            required=frozenset({"holder", "epoch", "ttl_s"}),
            optional=frozenset({"scope", "action", "released"}),
        ),
        # a replica-lifecycle command from the lease holder; `epoch` +
        # `holder` are checked against the target's own lease view (a
        # stale or split-brain-losing controller cannot drain nodes)
        _fs(
            P.FLEET_ACTION,
            required=frozenset({"rid", "action", "epoch", "holder"}),
            optional=frozenset({"state", "model", "reason"}),
        ),
        _fs(
            P.FLEET_ACK,
            required=frozenset({"rid"}),
            optional=frozenset({"ok", "error", "info"}),
        ),
        # multi-adapter residency update (adapters/): `service` names the
        # local service whose pool changed, `adapters` the now-resident
        # names, `models` the full per-adapter model-name list
        # ("<base>:<name>") receivers install into their provider tables
        _fs(
            P.ADAPTER_ANNOUNCE,
            required=frozenset({"peer_id", "service", "adapters"}),
            optional=frozenset({"models"}),
        ),
        # mesh-tiered speculative decoding (meshnet/draft.py): the serving
        # node streams one row's context to the draft-role peer. `base` is
        # the context length the server already holds for this rid (0 = full
        # resend), `tokens` the delta to append, `k` the draft width,
        # `model` the target model name (the server refuses a mismatched
        # drafter); {rid, done:true} frees the server-side row.
        _fs(
            P.DRAFT_REQUEST,
            required=frozenset({"rid"}),
            optional=frozenset({"base", "tokens", "k", "done", "model"}),
        ),
        # the draft answer: `pos` is the context length the draft continues
        # from (the client drops stale results after a rejection re-sync),
        # `draft` the proposed tokens, `reprime` asks for a full resend
        # (server lost/never had the row), `error` the typed failure
        _fs(
            P.DRAFT_RESULT,
            required=frozenset({"rid"}),
            optional=frozenset({"pos", "draft", "reprime", "error"}),
        ),
        # task protocol: per-kind field contracts live in TASK_SCHEMAS —
        # the TASK envelope itself only promises kind + correlation id
        _fs(P.TASK, required=frozenset({"kind", "task_id"}), allow_extra=True),
        _fs(
            P.RESULT,
            required=frozenset({"task_id"}),
            optional=frozenset({"ok", "info", "tokens", "stopped"}) | TRACE_KEYS,
        ),
        _fs(
            P.TASK_ERROR,
            required=frozenset({"task_id", "error"}),
            optional=frozenset({"error_kind"}),
        ),
        # reference worker-registration dialect: wire-compat constants we
        # keep but never construct (reference protocol.py:25-53)
        _fs(P.REGISTER, allow_extra=True),
        _fs(P.INFO, allow_extra=True),
    )
}


@dataclass(frozen=True)
class TaskSchema:
    """Field contract for one `task` kind (checked at run_stage_task call
    sites and task-frame literals; "kind"/"task_id" belong to the TASK
    envelope, tensors ride the binary frame, not these fields)."""

    kind: str
    required: frozenset = frozenset()
    optional: frozenset = frozenset()
    allow_extra: bool = False

    def allowed_keys(self) -> frozenset:
        return self.required | self.optional


_RELAY_FIELDS = frozenset({"origin_peer", "origin_task_id"})


def _ts(*args, **kw) -> TaskSchema:
    return TaskSchema(*args, **kw)


TASK_SCHEMAS: dict[str, TaskSchema] = {
    s.kind: s
    for s in (
        _ts(
            P.TASK_PART_LOAD,
            required=frozenset({"model", "n_stages", "stage"}),
            optional=frozenset(
                {
                    "max_seq_len",
                    "dtype",
                    "rng_seed",
                    "quantize",
                    "checkpoint_path",
                    "epoch",
                    "next_addr",
                }
            )
            | TRACE_KEYS,
        ),
        _ts(
            P.TASK_PART_FORWARD,
            required=frozenset({"model", "request_id", "offset"}),
            optional=frozenset({"write_mask", "gather", "epoch"}) | TRACE_KEYS,
        ),
        _ts(
            P.TASK_PART_FORWARD_RELAY,
            required=frozenset({"model", "request_id", "offset"}),
            optional=frozenset({"write_mask", "gather", "epoch"})
            | _RELAY_FIELDS
            | TRACE_KEYS,
        ),
        _ts(
            P.TASK_DECODE_RUN,
            required=frozenset({"model", "request_id", "offset"}),
            optional=frozenset(
                {"token", "k", "eos", "gather", "temperature", "seed", "epoch"}
            )
            | _RELAY_FIELDS
            | TRACE_KEYS,
        ),
        _ts(
            P.TASK_LAYER_FORWARD_TRAIN,
            required=frozenset({"model", "request_id"}),
        ),
        _ts(
            P.TASK_LAYER_BACKWARD,
            required=frozenset({"model", "request_id"}),
            optional=frozenset({"lr"}),
        ),
        _ts("part_release", required=frozenset({"model", "request_id"})),
        # reference worker kinds we keep for wire compat but never send
        _ts(P.TASK_LAYER_FORWARD, allow_extra=True),
        _ts(P.TASK_MODEL_LOAD, allow_extra=True),
        _ts(P.TASK_MODEL_INFER, allow_extra=True),
        _ts(P.TASK_MODEL_UNLOAD, allow_extra=True),
        _ts(P.TASK_TRAIN_STEP, allow_extra=True),
    )
}

# local-only annotations that never hit the wire: decode_binary hangs the
# tensor dict off the message under "_tensors"
LOCAL_KEYS = frozenset({"_tensors"})


def declared_key_universe() -> frozenset:
    """Every key any declared frame may carry — the reads check (ML-F003)
    flags message-dict lookups outside this set."""
    keys: set = set(LOCAL_KEYS) | set(P.SAMPLING_KEYS)
    for schema in FRAME_SCHEMAS.values():
        keys |= schema.allowed_keys()
    for task in TASK_SCHEMAS.values():
        keys |= task.allowed_keys()
    return frozenset(keys)
