"""Pass family 4: telemetry hygiene (ML-T*).

Span and metric NAMES are the aggregation keys of the whole observability
layer: the tracer groups percentiles per span name, and every distinct
metric name (or label value) is one Prometheus series forever. A name
built per request — ``span(f"gen.{rid}")`` — silently defeats the
per-name aggregation and grows the series table without bound (label/
cardinality explosion). Request-varying data belongs in span ATTRS or
metric LABELS (which are themselves chosen from bounded sets), never in
the name.

- ML-T001 — the name argument of a ``span(...)`` / ``annotate(...)`` /
  ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` call is built
  dynamically: an f-string, a ``%`` / ``+`` expression, or ``.format()``.
  Plain variables pass (a forwarding helper like ``tracing.annotate`` is
  fine — the literal lives at ITS call site and is checked there).

Scope: the whole package — telemetry calls live in engine/, meshnet/,
services/, web/ and api.py alike.
"""

from __future__ import annotations

import ast

# call targets whose first argument is a span/metric NAME. "count" is
# deliberately absent: str.count / list.count collisions would drown the
# rule in false positives, and Tracer.count shares the counters dict with
# bounded literal callers anyway.
_NAME_CALLS = frozenset({"span", "annotate", "counter", "gauge", "histogram"})


def _last_attr(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _dynamic_kind(expr: ast.AST) -> str | None:
    """How the expression builds a string at runtime, or None when it
    doesn't (constants and plain variables both pass)."""
    if isinstance(expr, ast.JoinedStr):
        return "f-string"
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, (ast.Add, ast.Mod)):
        return "concatenation" if isinstance(expr.op, ast.Add) else "%-format"
    if isinstance(expr, ast.Call) and _last_attr(expr.func) == "format":
        return ".format() call"
    return None


class TelemetryPass:
    family = "telemetry"
    rules = {
        "ML-T001": "span/metric name built dynamically (f-string/%/+/format)",
    }

    def applies(self, path: str) -> bool:
        return True  # telemetry calls live everywhere in the package

    def run(self, ctx) -> list:
        findings: list = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _last_attr(node.func) not in _NAME_CALLS:
                continue
            name_arg = None
            if node.args:
                name_arg = node.args[0]
            else:
                for kw in node.keywords:
                    if kw.arg == "name":
                        name_arg = kw.value
                        break
            if name_arg is None:
                continue
            kind = _dynamic_kind(name_arg)
            if kind is None:
                continue
            findings.append(
                ctx.finding(
                    "ML-T001",
                    name_arg,
                    f"span/metric name built via {kind} — names are "
                    "aggregation keys and every distinct one is a series "
                    "forever",
                    hint="use a literal dotted constant name; put the "
                    "varying part in span attrs / metric labels",
                )
            )
        return findings
