"""meshlint CLI: ``python -m bee2bee_tpu.analysis [paths...]``.

Exit codes: 0 = clean (or everything grandfathered/baselined), 1 = new
findings, 2 = usage error. Default target is the bee2bee_tpu package;
default baseline is analysis/baseline.json next to this file.
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import (
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    analyze_paths,
    filter_baselined,
    load_baseline,
    rule_catalog,
    write_baseline,
)

FAMILIES = ("frames", "async", "jax", "telemetry", "clock", "race")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bee2bee_tpu.analysis",
        description="meshlint: wire-protocol, async-safety and JAX-hygiene "
        "static analysis for the bee2bee-tpu mesh (docs/ANALYSIS.md)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to analyze (default: {PACKAGE_ROOT})")
    ap.add_argument("--rules", default=None,
                    help="comma-separated pass families to run "
                    f"(default: all of {','.join(FAMILIES)})")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                    "(ratchet maintenance: do this only to REMOVE fixed entries)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(rule_catalog().items()):
            print(f"{rule}  {desc}")
        return 0

    families = None
    if args.rules:
        families = frozenset(f.strip() for f in args.rules.split(",") if f.strip())
        unknown = families - set(FAMILIES)
        if unknown:
            print(f"unknown pass families: {sorted(unknown)} "
                  f"(have: {FAMILIES})", file=sys.stderr)
            return 2

    paths = args.paths or [PACKAGE_ROOT]
    findings = analyze_paths(paths, families)

    if args.write_baseline:
        out = write_baseline(findings, args.baseline)
        print(f"baseline: {len(findings)} finding(s) written to {out}")
        return 0

    if args.no_baseline:
        new, old = findings, []
    else:
        new, old = filter_baselined(findings, load_baseline(args.baseline))

    if args.as_json:
        print(json.dumps(
            {
                "new": [f.to_dict() for f in new],
                "baselined": [f.to_dict() for f in old],
            },
            indent=2,
        ))
    else:
        for f in new:
            print(f.render())
        tail = f"meshlint: {len(new)} new finding(s)"
        if old and not args.no_baseline:
            tail += f", {len(old)} grandfathered (analysis/baseline.json)"
        print(tail)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
