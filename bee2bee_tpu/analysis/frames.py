"""Pass family 1: protocol-frame checking (ML-F*).

The wire contract deliberately ignores unknown JSON keys (wire compat with
the reference mesh), which turns every typo'd key into a silently-wrong
output instead of an error. This pass re-creates the missing error at
build time by checking, against the schema registry (analysis/schema.py):

- ML-F001 — frame construction with an undeclared key
  (`protocol.msg(OP, typo=...)`, `{"type": OP, "typo": ...}`, or a
  `run_stage_task(peer, KIND, {...})` fields dict)
- ML-F002 — frame construction missing a required key
- ML-F003 — message-dict read (`data.get("k")` / `data["k"]`) of a key no
  declared frame carries
- ML-F004 — a gen_request built without forwarding the sampling knobs
  (protocol.SAMPLING_KEYS): the exact "knob dropped at one hop" bug class
  protocol.py warns about

Scope: meshnet/, web/, services/, fleet/, api.py — everywhere frames are
built or consumed.
"""

from __future__ import annotations

import ast

from .. import protocol as P
from .schema import FRAME_SCHEMAS, TASK_SCHEMAS, declared_key_universe

# functions whose dict-ish parameter is a decoded wire message (the mesh's
# handler/worker naming convention); decode()-assigned variables are
# tracked regardless of function name
_HANDLER_PREFIXES = ("_handle_", "_task_", "_on_", "_run_stage", "_ring_")
_MESSAGE_PARAM_NAMES = ("data", "msg", "message", "frame")

_SCOPES = ("meshnet/", "web/", "services/", "fleet/")


class _ProtocolNames:
    """Resolve AST expressions to protocol constant values for this file."""

    def __init__(self, tree: ast.AST):
        self.module_aliases: set[str] = set()
        self.const_names: dict[str, str] = {}
        self.msg_names: set[str] = set()  # bare names bound to protocol.msg
        self.copy_sampling_names: set[str] = {"copy_sampling"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[-1] == "protocol":
                        self.module_aliases.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.endswith("protocol"):
                    for a in node.names:
                        if a.name == "msg":
                            self.msg_names.add(a.asname or a.name)
                        elif a.name == "copy_sampling":
                            self.copy_sampling_names.add(a.asname or a.name)
                        else:
                            val = getattr(P, a.name, None)
                            if isinstance(val, str):
                                self.const_names[a.asname or a.name] = val
                else:
                    for a in node.names:
                        if a.name == "protocol":
                            self.module_aliases.add(a.asname or "protocol")

    def resolve(self, expr: ast.AST) -> str | None:
        """Expression → op/kind string, or None when not statically known."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            return self.const_names.get(expr.id)
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in self.module_aliases
        ):
            val = getattr(P, expr.attr, None)
            return val if isinstance(val, str) else None
        return None

    def is_msg_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id in self.msg_names
        return (
            isinstance(f, ast.Attribute)
            and f.attr == "msg"
            and isinstance(f.value, ast.Name)
            and f.value.id in self.module_aliases
        )


def _call_name(expr: ast.AST) -> str:
    """Last dotted component of a call target ("send" for self._send)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


class FramesPass:
    family = "frames"
    rules = {
        "ML-F001": "frame constructed with a key no schema declares",
        "ML-F002": "frame constructed without a required key",
        "ML-F003": "message-dict read of a key no declared frame carries",
        "ML-F004": "gen_request built without forwarding SAMPLING_KEYS",
    }

    def applies(self, path: str) -> bool:
        return path.startswith(_SCOPES) or path == "api.py"

    def run(self, ctx) -> list:
        names = _ProtocolNames(ctx.tree)
        universe = declared_key_universe()
        findings: list = []
        self._walk_scope(ctx, names, universe, ctx.tree, _FnInfo(None), findings)
        return findings

    # ------------------------------------------------------------ traversal

    def _walk_scope(self, ctx, names, universe, scope, fn, findings):
        """Visit one function (or module) scope; recurse into nested
        functions with their own _FnInfo."""
        body = scope.body if hasattr(scope, "body") else []
        for node in body:
            self._visit(ctx, names, universe, node, fn, findings)

    def _visit(self, ctx, names, universe, node, fn, findings):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = _FnInfo(node)
            self._walk_scope(ctx, names, universe, node, inner, findings)
            self._check_fn_gen_requests(ctx, inner, findings)
            return
        if isinstance(node, ast.Assign):
            self._track_assign(names, node, fn)
        if isinstance(node, ast.Call):
            self._check_call(ctx, names, universe, node, fn, findings)
        elif isinstance(node, ast.Dict):
            self._check_dict_literal(ctx, names, node, fn, findings)
        elif isinstance(node, ast.Subscript):
            self._check_subscript(ctx, universe, node, fn, findings)
        for child in ast.iter_child_nodes(node):
            self._visit(ctx, names, universe, child, fn, findings)

    def _track_assign(self, names, node: ast.Assign, fn):
        value = node.value
        # fields = { ... }  → resolvable at run_stage_task call sites
        if isinstance(value, ast.Dict) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                # only single-assignment names are trusted
                fn.local_dicts[t.id] = (
                    None if t.id in fn.local_dicts else value
                )
                fn.frame_names[id(value)] = t.id
        # m = protocol.msg(...): the name copy_sampling may later target
        if (
            isinstance(value, ast.Call)
            and names.is_msg_call(value)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            fn.frame_names[id(value)] = node.targets[0].id
        # data = protocol.decode(raw) / data, tensors = decode_binary(raw)
        if isinstance(value, ast.Call):
            cname = _call_name(value.func)
            if cname == "decode":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        fn.message_vars.add(t.id)
            elif cname == "decode_binary":
                for t in node.targets:
                    if isinstance(t, ast.Tuple) and t.elts:
                        first = t.elts[0]
                        if isinstance(first, ast.Name):
                            fn.message_vars.add(first.id)

    # ------------------------------------------------------------- checkers

    def _check_call(self, ctx, names, universe, call: ast.Call, fn, findings):
        if names.is_msg_call(call) and call.args:
            op = names.resolve(call.args[0])
            if op is not None:
                keys = {kw.arg for kw in call.keywords if kw.arg is not None}
                dynamic = any(kw.arg is None for kw in call.keywords)
                self._check_frame(ctx, call, op, keys, dynamic, findings)
                fn.note_frame(op, keys, dynamic, call)
            return
        if _call_name(call.func) == "run_stage_task" and len(call.args) >= 3:
            kind = names.resolve(call.args[1])
            fields = call.args[2]
            if isinstance(fields, ast.Name):
                fields = fn.local_dicts.get(fields.id)
            if kind is not None and isinstance(fields, ast.Dict):
                self._check_task_fields(ctx, call, kind, fields, findings)
        if (
            _call_name(call.func) in names.copy_sampling_names
            and len(call.args) >= 2
            and isinstance(call.args[1], ast.Name)
        ):
            fn.copy_sampling_targets.add(call.args[1].id)
        if _call_name(call.func) == "get" and call.args:
            # data.get("key"): reads on known message dicts
            f = call.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id in fn.message_vars
            ):
                key = call.args[0]
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    self._check_read(ctx, universe, call, key.value, findings)

    def _check_subscript(self, ctx, universe, node: ast.Subscript, fn, findings):
        if not (isinstance(node.value, ast.Name) and node.value.id in fn.message_vars):
            return
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            self._check_read(ctx, universe, node, sl.value, findings)

    def _check_read(self, ctx, universe, node, key: str, findings):
        if key not in universe:
            findings.append(
                ctx.finding(
                    "ML-F003",
                    node,
                    f"read of message key {key!r} that no declared frame carries",
                    "typo, or a protocol change that skipped the schema "
                    "registry — fix the key or extend analysis/schema.py",
                )
            )

    def _check_dict_literal(self, ctx, names, node: ast.Dict, fn, findings):
        keys: set[str] = set()
        op = None
        dynamic = False
        for k, v in zip(node.keys, node.values):
            if k is None:  # {**spread}
                dynamic = True
                continue
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
                if k.value == "type":
                    op = names.resolve(v)
        if op is None or op not in FRAME_SCHEMAS:
            return
        self._check_frame(ctx, node, op, keys - {"type"}, dynamic, findings)
        fn.note_frame(op, keys - {"type"}, dynamic, node)

    def _check_frame(self, ctx, node, op: str, keys: set, dynamic: bool, findings):
        schema = FRAME_SCHEMAS.get(op)
        if schema is None:
            findings.append(
                ctx.finding(
                    "ML-F001",
                    node,
                    f"unknown frame op {op!r}",
                    "not in protocol.MESSAGE_TYPES-derived registry — add a "
                    "FrameSchema in analysis/schema.py",
                )
            )
            return
        if not schema.allow_extra:
            for k in sorted(keys - schema.allowed_keys()):
                findings.append(
                    ctx.finding(
                        "ML-F001",
                        node,
                        f"undeclared key {k!r} on a {op!r} frame",
                        "the wire silently drops unknown keys — fix the typo "
                        "or declare the key in analysis/schema.py",
                    )
                )
        if not dynamic:
            for k in sorted(schema.required - keys):
                findings.append(
                    ctx.finding(
                        "ML-F002",
                        node,
                        f"{op!r} frame missing required key {k!r}",
                        f"every {op!r} frame must carry {sorted(schema.required)}",
                    )
                )
            for group in schema.required_any:
                if not (keys & group):
                    findings.append(
                        ctx.finding(
                            "ML-F002",
                            node,
                            f"{op!r} frame missing a correlation id "
                            f"(one of {sorted(group)})",
                            "replies are matched by rid/task_id; a frame "
                            "without one is unanswerable",
                        )
                    )

    def _check_task_fields(self, ctx, call, kind: str, fields: ast.Dict, findings):
        schema = TASK_SCHEMAS.get(kind)
        if schema is None:
            findings.append(
                ctx.finding(
                    "ML-F001",
                    call,
                    f"unknown task kind {kind!r}",
                    "add a TaskSchema in analysis/schema.py",
                )
            )
            return
        keys: set[str] = set()
        dynamic = False
        for k in fields.keys:
            if k is None:
                dynamic = True
            elif isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
        if not schema.allow_extra:
            for k in sorted(keys - schema.allowed_keys()):
                findings.append(
                    ctx.finding(
                        "ML-F001",
                        call,
                        f"undeclared field {k!r} on task kind {kind!r}",
                        "the worker reads only declared fields — fix the "
                        "typo or extend TASK_SCHEMAS in analysis/schema.py",
                    )
                )
        if not dynamic:
            for k in sorted(schema.required - keys):
                findings.append(
                    ctx.finding(
                        "ML-F002",
                        call,
                        f"task kind {kind!r} missing required field {k!r}",
                        f"workers require {sorted(schema.required)} for {kind!r}",
                    )
                )

    def _check_fn_gen_requests(self, ctx, fn, findings):
        """ML-F004, attributed per FRAME: a gen_request is exempt only when
        it spreads dynamic kwargs, carries a sampling knob explicitly, or
        is assigned to a name that some copy_sampling call in the function
        targets as its dst — a copy_sampling aimed at a DIFFERENT frame
        doesn't cover this one."""
        sampling = set(P.SAMPLING_KEYS)
        for keys, dynamic, node in fn.gen_requests:
            if dynamic or keys & sampling:
                continue
            name = fn.frame_names.get(id(node))
            if name and name in fn.copy_sampling_targets:
                continue
            findings.append(
                ctx.finding(
                    "ML-F004",
                    node,
                    "gen_request built without forwarding the sampling knobs",
                    "a knob missing at ANY hop is a silently-wrong output "
                    "(protocol.py SAMPLING_KEYS) — protocol.copy_sampling "
                    "the source dict into this frame",
                )
            )


class _FnInfo:
    """Per-function-scope facts the frames pass accumulates."""

    def __init__(self, node):
        self.node = node
        self.local_dicts: dict[str, ast.Dict | None] = {}
        self.message_vars: set[str] = set()
        self.gen_requests: list[tuple[set, bool, ast.AST]] = []
        self.frame_names: dict[int, str] = {}  # id(frame node) -> bound name
        self.copy_sampling_targets: set[str] = set()  # dst names copied into
        if node is not None and node.name.startswith(_HANDLER_PREFIXES):
            for arg in list(node.args.args) + list(node.args.kwonlyargs):
                if arg.arg in _MESSAGE_PARAM_NAMES:
                    self.message_vars.add(arg.arg)

    def note_frame(self, op: str, keys: set, dynamic: bool, node) -> None:
        if op == P.GEN_REQUEST and self.node is not None:
            self.gen_requests.append((keys, dynamic, node))
