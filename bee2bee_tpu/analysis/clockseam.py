"""Pass family 5: clock-seam (ML-C*).

The meshnet/fleet/router/health control planes are deterministic-sim
capable: every timestamp, backoff, and timer routes through the injected
``Clock`` (bee2bee_tpu/clock.py), so ``simnet`` can replace wall time
with a virtual clock and replay 200-node chaos runs bit-identically.
One stray ``time.time()`` silently re-couples a code path to the host
clock — the sim still *runs*, but traces stop being replayable and
virtual-time tests flake under load. Rule:

- ML-C001 — direct wall-clock read or bare asyncio timer
  (``time.time/monotonic/perf_counter/sleep``, ``asyncio.sleep``,
  ``asyncio.wait_for``) inside a clock-seamed package (``meshnet/``,
  ``fleet/``, ``router/``, ``health.py``). Use the seam instead:
  ``self.clock.time()`` / ``self.clock.sleep()`` /
  ``self.clock.wait_for()`` (or ``get_clock()`` where no instance is in
  scope). Genuine wall-clock interactions — NAT round trips, thread
  joins — carry ``# meshlint: ignore[ML-C001] -- reason``.

The baseline for this family is empty and must stay empty: the seam was
installed package-wide in the same PR that added the rule.
"""

from __future__ import annotations

import ast

from .core import dotted_name as _dotted

# direct wall-clock / loop-timer targets by dotted name. `self.clock.sleep`
# resolves to "self.clock.sleep" — never matched; only the bare module
# calls are findings.
_WALL_CLOCK = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.sleep",
    "asyncio.sleep",
    "asyncio.wait_for",
}

_SEAM_FOR = {
    "time.time": "clock.time()",
    "time.monotonic": "clock.monotonic()",
    "time.perf_counter": "clock.monotonic()",
    "time.sleep": "await clock.sleep()",
    "asyncio.sleep": "await clock.sleep()",
    "asyncio.wait_for": "await clock.wait_for()",
}

_SEAMED_PREFIXES = ("meshnet/", "fleet/", "router/")
_SEAMED_FILES = {"health.py"}


class ClockSeamPass:
    family = "clock"
    rules = {
        "ML-C001": "direct wall-clock call in a clock-seamed package",
    }

    def applies(self, path: str) -> bool:
        return path.startswith(_SEAMED_PREFIXES) or path in _SEAMED_FILES

    def run(self, ctx) -> list:
        findings: list = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name not in _WALL_CLOCK:
                continue
            findings.append(
                ctx.finding(
                    "ML-C001",
                    node,
                    f"direct {name}() in a clock-seamed package",
                    f"breaks deterministic simulation — route through the "
                    f"injected clock ({_SEAM_FOR[name]}; resolve via "
                    f"get_clock() if no instance is in scope), or justify "
                    f"with # meshlint: ignore[ML-C001] -- reason",
                )
            )
        return findings
