"""meshlint: purpose-built static analysis for the bee2bee-tpu mesh.

Three pass families turn the codebase's load-bearing conventions into
machine-checked invariants (rule catalog: docs/ANALYSIS.md):

- **frames** (ML-F*) — every wire-frame construction and message-dict read
  in meshnet/, web/, services/ and api.py checked against the per-op
  schema registry (analysis/schema.py); catches the typo'd-key bug class
  the wire protocol swallows by design.
- **async** (ML-A*) — blocking calls inside ``async def``, unbounded
  network awaits on mesh hot paths, network awaits under an asyncio lock.
- **jax** (ML-J*) — implicit host syncs and Python branches on traced
  values inside jit-compiled functions in engine/, models/, ops/,
  parallel/.
- **race** (ML-R*) — async interleaving hazards in the mesh control
  plane: check-then-act split across an await, dropped create_task
  handles, unlocked multi-entry container mutation, await inside
  iteration over shared state (dynamic twin: the simnet interleaving
  fuzzer).

CLI: ``python -m bee2bee_tpu.analysis [paths...]`` (exit 1 on any finding
not grandfathered by analysis/baseline.json). Library:
``analyze_paths([...])`` / ``analyze_source(src, "meshnet/x.py")``.
Deliberate violations: ``# meshlint: ignore[rule-id] -- reason``.
"""

from .core import (
    BAD_SUPPRESSION,
    DEFAULT_BASELINE,
    Finding,
    analyze_paths,
    analyze_source,
    filter_baselined,
    load_baseline,
    rule_catalog,
    write_baseline,
)
from .schema import FRAME_SCHEMAS, TASK_SCHEMAS, declared_key_universe

__all__ = [
    "BAD_SUPPRESSION",
    "DEFAULT_BASELINE",
    "FRAME_SCHEMAS",
    "Finding",
    "TASK_SCHEMAS",
    "analyze_paths",
    "analyze_source",
    "declared_key_universe",
    "filter_baselined",
    "load_baseline",
    "rule_catalog",
    "write_baseline",
]
