"""Standby provisioning: the scale-OUT half of the control loop.

A standby replica is a mesh node whose telemetry digest advertises
``fleet_state: "standby"`` — it is connected and gossiping but the
router (router/policy.py) and the migration plane exclude it from every
traffic decision. Scaling out walks it through three states, and the
ordering IS the robustness contract:

1. **activate** — a ``fleet_action`` flips it to ``warming`` and runs
   the node's ``fleet_provision_cb`` (real deployments: weight prefetch
   via pieces/DHT, ``meshnet.weights.serve_model_from_mesh``; tests and
   the bench boot a service in-process). Warming is still
   router-excluded.
2. **probe** — the controller drives a real warm-up generation through
   the ordinary p2p serving path (``request_generation``). This is the
   gate: a replica that cannot serve one generation never becomes
   eligible, no matter what its digest claims.
3. **flip eligible** — only after the probe passes, ``set_state active``
   clears the fleet state and the next gossip makes the replica
   routable.

Any failure rolls the node back to ``standby`` (never left ``warming``
— an orphaned warming node would otherwise be invisible capacity) and
journals a ``fleet:provision_failed`` incident. A controller that dies
mid-provision leaves the node warming; the successor's orphan scan
(controller.py) re-runs the probe and completes or rolls back.
"""

from __future__ import annotations

import asyncio
import logging

logger = logging.getLogger("bee2bee_tpu.fleet")


def _model_matches(model: str | None, models) -> bool:
    """The mesh's fuzzy model-match rule (node.local_service_for)."""
    if model is None:
        return True
    return any(
        model.lower() in str(m).lower() or str(m).lower() in model.lower()
        for m in models or []
    )


class Provisioner:
    """Scale-out orchestration for one FleetController. Separated so the
    chaos harness (meshnet/chaos.py ChaosController) can fault exactly
    the probe seam without touching the decision loop."""

    def __init__(self, controller):
        self.controller = controller

    @property
    def node(self):
        return self.controller.node

    @property
    def config(self):
        return self.controller.config

    # ------------------------------------------------------------- picking

    def pick_standby(self, digests: dict[str, dict]) -> str | None:
        """Deterministic standby pick: the smallest peer id advertising
        ``fleet_state: "standby"`` in a FRESH digest. Determinism matters
        for the takeover story — a successor re-deciding the same fleet
        state picks the same node."""
        cands = sorted(
            pid
            for pid, d in digests.items()
            if isinstance(d, dict)
            and d.get("fleet_state") == "standby"
            and not d.get("draining")
            and pid != self.node.peer_id
        )
        return cands[0] if cands else None

    # ------------------------------------------------------------ scale out

    async def scale_out(self, target: str, adopted: bool = False) -> tuple[bool, str]:
        """Walk one standby to router-eligible: activate → await the
        service advertisement → warm-up probe → flip active. With
        ``adopted`` (orphan-scan path) the node is already warming from a
        dead controller's attempt — skip straight to the probe. Returns
        (ok, detail); the node is back in ``standby`` on every failure."""
        cfg = self.config
        ctrl = self.controller
        if not adopted:
            ctrl.set_action_phase("activating")
            ack = await ctrl.send_action(
                target, "activate",
                timeout=cfg.ack_timeout_s + cfg.settle_timeout_s,
                **({"model": cfg.model} if cfg.model else {}),
            )
            if not ack.get("ok"):
                # activate failed node-side: the target already reverted
                # itself to standby (the action handler's contract)
                return False, f"activate failed: {ack.get('error')}"
        ctrl.set_action_phase("probing")
        if not await self._await_service(target):
            await ctrl.send_action(target, "set_state", state="standby")
            return False, "service never advertised within settle window"
        ok, detail = await self.probe(target)
        if not ok:
            await ctrl.send_action(target, "set_state", state="standby")
            return False, detail
        ack = await ctrl.send_action(target, "set_state", state="active")
        if not ack.get("ok"):
            await ctrl.send_action(target, "set_state", state="standby")
            return False, f"flip to active failed: {ack.get('error')}"
        return True, detail

    async def probe(self, target: str) -> tuple[bool, str]:
        """The warm-up generation gate, via the ordinary serving path.
        The chaos harness wraps exactly this method."""
        cfg = self.config
        clock = self.controller.clock
        try:
            t0 = clock.monotonic()
            result = await self.node.request_generation(
                target,
                cfg.probe_prompt,
                model=cfg.model,
                max_new_tokens=cfg.probe_tokens,
                temperature=0.0,
                timeout=cfg.probe_timeout_s,
            )
            if not isinstance(result, dict) or result.get("error"):
                return False, f"probe error: {(result or {}).get('error')}"
            ms = (clock.monotonic() - t0) * 1000.0
            return True, f"probe ok in {ms:.0f}ms"
        except Exception as e:  # noqa: BLE001 — a failed probe is a verdict
            return False, f"probe failed: {e}"

    async def _await_service(self, target: str) -> bool:
        """Wait (bounded) for the activated node's service announce to
        land in our provider table — the probe needs a service name to
        address."""
        cfg = self.config
        clock = self.controller.clock
        deadline = clock.monotonic() + cfg.settle_timeout_s
        while clock.monotonic() < deadline:
            svcs = self.node.providers.get(target) or {}
            for meta in list(svcs.values()):
                if _model_matches(cfg.model, meta.get("models")):
                    return True
            await clock.sleep(0.05)
        return False
