"""FleetController: burn-rate-driven scale-out/in with chaos-proof leasing.

One controller per operator scope holds the TTL'd lease (lease.py) and,
on the node's monitor cadence, turns the health plane's fleet aggregates
(health.controller_aggregates over the gossiped digests — the same data
``/mesh/health`` serves) into replica lifecycle actions:

- **scale OUT** when fast-burn is fleet-wide (``burn_quorum`` of the
  eligible replicas report a burning/tripped SLO brief) and *sustained*
  (``out_sustain_ticks`` consecutive ticks): pick a standby, activate →
  probe → flip eligible (provision.py — never eligible before the probe
  passes);
- **scale IN** when headroom is sustained across the slow window
  (``in_sustain_ticks`` ticks of zero burning replicas + low batch fill
  + low queue wait): pick the telemetry-WORST eligible node (the
  router's own penalty scorer, inverted) and invoke the existing
  drain+migrate path, finishing by converting the drained node to a
  warm standby — the fleet breathes instead of discarding capacity.

Hysteresis guards every action: sustain streaks, per-direction
cooldowns (any completed action refreshes both — no out/in flapping),
min/max replica bounds, and ONE in-flight action at a time. Every
decision (noops included) lands in a bounded journal served at
``GET /fleet``; every action outcome is a typed ``fleet:*`` incident
bundle in the flight recorder.

Chaos-proofing is structural, not bolted on: the in-flight action rides
the lease gossip, every leader tick re-scans the fleet for orphaned
state (a peer left ``draining`` or ``warming`` by a dead or partitioned
predecessor) and adopts or rolls it back, and replica actions are
epoch-gated at the target so a split-brain loser cannot drain nodes.
``tests/test_fleet.py`` pins the matrix via ChaosController.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
from collections import deque
from dataclasses import asdict, dataclass, fields

from .. import protocol
from ..clock import get_clock
from ..health import controller_aggregates
from ..metrics import get_registry
from ..utils import load_json_source, new_id
from .lease import LeaseKeeper
from .provision import Provisioner, _model_matches

logger = logging.getLogger("bee2bee_tpu.fleet")

# decision/action observability. Label sets are closed (decision kinds
# and action kinds below), so cardinality is bounded.
_C_DECISIONS = get_registry().counter(
    "fleet.decisions", "controller decisions by kind (noop included)"
)
_C_ACTIONS = get_registry().counter(
    "fleet.actions", "completed controller actions by kind and outcome"
)
_G_LEADER = get_registry().gauge(
    "fleet.leader", "1 while this node holds the controller lease"
)
_G_REPLICAS = get_registry().gauge(
    "fleet.eligible_replicas", "router-eligible serving replicas (leader view)"
)


@dataclass(frozen=True)
class FleetConfig:
    """Controller knobs (``BEE2BEE_FLEET_CONFIG``, inline JSON or a
    path — the SLO/tenants/admission/router convention, validated
    loudly at construction)."""

    scope: str = "default"
    model: str | None = None      # serving scope; None = any service
    min_replicas: int = 1
    max_replicas: int = 8
    burn_quorum: float = 0.5      # fraction of eligible replicas burning
    # that counts as "fleet-wide" (one hot node is a routing problem, not
    # a capacity problem)
    out_sustain_ticks: int = 2    # consecutive burning ticks before out
    in_sustain_ticks: int = 6     # consecutive headroom ticks before in
    # (ticks ride the ping cadence: the slow direction is deliberately
    # several times the fast one, Google-SRE multi-window style)
    headroom_fill_max: float = 0.35
    headroom_queue_p95_ms: float = 250.0
    # pool-occupancy trend forecast (ISSUE 20): scale out when the
    # observatory's trend digest projects some eligible replica's paged
    # pool exhausting within this horizon (aggregates' pool_eta_s, from
    # the gossiped pool_free_frac slope) — capacity arrives BEFORE the
    # instantaneous burn does, instead of only reacting to it. Same
    # sustain/cooldown/standby ladder as the burn path; 0 disables.
    pool_eta_out_s: float = 120.0
    scale_out_cooldown_s: float = 30.0
    scale_in_cooldown_s: float = 120.0
    ack_timeout_s: float = 10.0       # fleet_action round-trip bound
    settle_timeout_s: float = 30.0    # activate → service advertised
    probe_timeout_s: float = 60.0     # warm-up generation bound
    probe_tokens: int = 4
    probe_prompt: str = "fleet warm-up probe"
    action_timeout_s: float = 120.0   # whole-action bound (drain quiesce)
    lease_ttl_s: float | None = None  # None → 3 × the node's ping cadence
    claim_stagger_s: float | None = None  # None → lease_ttl / 3 per rank


def parse_fleet_config(obj) -> FleetConfig:
    if not isinstance(obj, dict):
        raise ValueError(
            f"fleet config must be a JSON object, got {type(obj).__name__}"
        )
    known = {f.name for f in fields(FleetConfig)}
    unknown = set(obj) - known
    if unknown:
        raise ValueError(f"fleet config: unknown keys {sorted(unknown)}")
    kwargs = {}
    for k, v in obj.items():
        if k in ("scope", "model", "probe_prompt"):
            kwargs[k] = None if v is None else str(v)
            continue
        if v is None and k in ("lease_ttl_s", "claim_stagger_s"):
            kwargs[k] = None
            continue
        kwargs[k] = int(v) if k in (
            "min_replicas", "max_replicas", "out_sustain_ticks",
            "in_sustain_ticks", "probe_tokens",
        ) else float(v)
        if kwargs[k] < 0:
            raise ValueError(f"fleet config: {k} must be >= 0")
    cfg = FleetConfig(**kwargs)
    if cfg.min_replicas > cfg.max_replicas:
        raise ValueError("fleet config: min_replicas > max_replicas")
    if not 0.0 < cfg.burn_quorum <= 1.0:
        raise ValueError("fleet config: burn_quorum must be in (0, 1]")
    return cfg


def load_fleet_config(source: str | None = None) -> FleetConfig:
    data = load_json_source(source, "BEE2BEE_FLEET_CONFIG")
    return parse_fleet_config(data) if data is not None else FleetConfig()


class FleetController:
    """Lives on EVERY node (the lease keeper and the action handler must
    — any node can be commanded); only ``enabled`` nodes compete for the
    lease and run the decision loop. ``tick()`` rides the node's monitor
    loop (the ping cadence) and is directly callable for deterministic
    tests."""

    # journal decision kinds (closed set — the counter label)
    D_NOOP = "noop"
    D_SCALE_OUT = "scale_out"
    D_SCALE_IN = "scale_in"
    D_ADOPT = "adopt"
    D_ROLLBACK = "rollback"
    D_INFLIGHT = "inflight"
    D_PAUSED = "paused"
    D_OVERRIDE = "override"

    def __init__(self, node, enabled: bool | None = None,
                 config: FleetConfig | None = None):
        self.node = node
        if enabled is None:
            env = (os.environ.get("BEE2BEE_FLEET") or "").strip().lower()
            enabled = env in ("1", "true", "on", "controller")
        self.enabled = bool(enabled)
        # load_fleet_config raises on malformed BEE2BEE_FLEET_CONFIG —
        # same fail-at-construction contract as the SLO/router configs
        self.config = config or load_fleet_config()
        ttl = self.config.lease_ttl_s or 3.0 * node.ping_interval_s
        # the node's injected clock drives every fleet timer: lease TTLs,
        # action deadlines, cooldowns, drain polls (clock.py seam)
        self.clock = getattr(node, "clock", None) or get_clock()
        self.lease = LeaseKeeper(
            ttl_s=ttl, scope=self.config.scope, clock=self.clock
        )
        self.provisioner = Provisioner(self)
        self.is_leader = False
        self.epoch = 0
        self.paused = False
        self.decisions: deque[dict] = deque(maxlen=64)
        self.stats = {
            "takeovers": 0, "stepdowns": 0, "scale_out": 0, "scale_in": 0,
            "provision_failed": 0, "adopted": 0, "rolled_back": 0,
            "actions_failed": 0,
        }
        self._action: dict | None = None
        self._action_task: asyncio.Task | None = None
        # rid → (target peer, ws the action went out on, future): the
        # ack is only accepted from the addressed peer (see on_ack)
        self._acks: dict[str, tuple[str, object, asyncio.Future]] = {}
        self._burn_streak = 0
        self._headroom_streak = 0
        self._last_out = float("-inf")
        self._last_in = float("-inf")
        self._last_agg: dict = {}

    # ------------------------------------------------------- frame handlers

    @staticmethod
    def _advertises_controller(digest) -> bool:
        """THE controller-eligibility predicate — shared by the frame
        authorization gate (_controller_sender) and the takeover
        ranking (_claim_rank): the set a target obeys must be exactly
        the set that competes for the lease."""
        return bool(isinstance(digest, dict) and digest.get("fleet_controller"))

    def _controller_sender(self, pid: str) -> bool:
        """May this peer speak for the control plane at all? Leadership
        is restricted to controller-ELIGIBLE nodes (they advertise
        ``fleet_controller`` in their gossiped digest — the same set
        _claim_rank ranks), so a plain serving peer cannot claim a
        reign or command replicas no matter what epoch it invents. The
        mesh has no cryptographic identities — a peer that falsely
        advertises eligibility can still compete (Byzantine peers are
        out of scope) — but the bar matches the takeover protocol's own."""
        return self._advertises_controller(self.node.health.fresh().get(pid))

    async def on_lease(self, ws, data: dict) -> None:
        """FLEET_LEASE from a peer. Identity comes from the CONNECTION
        (like telemetry gossip): a peer can only claim the lease for
        itself, never forge another node's reign — and only a
        controller-eligible peer's claim counts at all."""
        pid = await self.node._peer_for(ws)
        if pid is None or data.get("holder") != pid:
            return
        if not self._controller_sender(pid):
            # benign on first contact (the lease broadcast can beat the
            # sender's first telemetry frame by one gossip round), but
            # an operator chasing "why does this node ignore the
            # leader" needs the drop to be visible
            logger.debug(
                "lease claim from %s dropped: no fresh controller-"
                "eligible digest for the sender yet", pid,
            )
            return
        view = self.lease.observe(data)
        if (
            self.is_leader
            and view is not None
            and view.fresh()
            and view.holder != self.node.peer_id
        ):
            # the ordering picked the rival: split-brain resolves the
            # moment the loser sees the winning frame
            self._step_down(f"superseded by {view.holder} epoch {view.epoch}")

    async def on_action(self, ws, data: dict) -> None:
        """FLEET_ACTION target side: apply one replica-lifecycle command
        from the (epoch-verified) lease holder, then gossip promptly so
        the fleet converges on the new state within one tick."""
        node = self.node
        rid = data.get("rid")
        act = data.get("action")
        # identity comes from the CONNECTION, exactly like on_lease: the
        # leader always issues its own actions over its own link, so a
        # frame whose claimed holder is not the sending peer is a forgery.
        # Drop it before lease.observe — a forged (holder, epoch) would
        # otherwise both command this node and poison its epoch floor.
        pid = await self.node._peer_for(ws)
        if pid is None or data.get("holder") != pid:
            return
        # and only a controller-ELIGIBLE peer may command at all: a
        # serving peer self-claiming an invented high epoch under its
        # own (connection-verified) identity must not drain the fleet
        # either. Typed nack — the refusal should be debuggable at the
        # sender, unlike the silent forgery drop above.
        if not self._controller_sender(pid):
            await self._ack(ws, rid, ok=False, error="not_controller")
            return
        if not self.lease.authorizes(data.get("holder"), data.get("epoch")):
            await self._ack(ws, rid, ok=False, error="stale_epoch")
            return
        # an authorized command also teaches us the claimant's reign —
        # relevant when the action arrives before its lease gossip
        self.lease.observe({
            "holder": data.get("holder"), "epoch": data.get("epoch"),
            "ttl_s": self.lease.ttl_s,
        })
        try:
            info = None
            if act == "drain":
                info = await node.begin_drain(wait=False, source="fleet")
            elif act == "undrain":
                node.end_drain()
            elif act == "to_standby":
                # scale-in completion: drained → warm standby. Order
                # matters — the standby state lands in the same digest
                # the drain flag leaves, so there is no eligible gap.
                node.fleet_state = "standby"
                node.end_drain()
            elif act == "activate":
                node.fleet_state = "warming"
                cb = getattr(node, "fleet_provision_cb", None)
                if cb is not None:
                    await cb(data.get("model"))
            elif act == "set_state":
                state = data.get("state")
                if state not in ("standby", "warming", "active"):
                    raise ValueError(f"unknown fleet state {state!r}")
                node.fleet_state = None if state == "active" else state
            else:
                raise ValueError(f"unknown fleet action {act!r}")
            node.recorder.record(
                "fleet_action", action=act, holder=data.get("holder"),
                epoch=data.get("epoch"),
            )
            with contextlib.suppress(Exception):
                await node.gossip_telemetry()
            await self._ack(ws, rid, ok=True, info=info)
        except Exception as e:  # noqa: BLE001 — the verdict is the reply
            if act == "activate":
                # a failed provision must not leave the node warming
                node.fleet_state = "standby"
            logger.exception("fleet action %s failed", act)
            await self._ack(ws, rid, ok=False, error=str(e))

    async def on_ack(self, ws, data: dict) -> None:
        entry = self._acks.get(data.get("rid"))
        if entry is None:
            return
        target, sent_ws, fut = entry
        # the ack must come from the peer the action was addressed to —
        # a peer that learns (or guesses) a rid cannot forge another
        # node's completion. The EXACT connection the action went out on
        # also counts: a mid-action hello rebind (dual-dial convergence)
        # re-registers the target onto a new ws while its genuine ack
        # rides the old link, and a completed drain booked as refused
        # would be worse than the (already-flagged) rebind itself.
        if ws is not sent_ws:
            pid = await self.node._peer_for(ws)
            if pid != target:
                return
        if not fut.done():
            fut.set_result({k: v for k, v in data.items() if k != "type"})

    async def _ack(self, ws, rid, ok: bool, error: str | None = None,
                   info: dict | None = None) -> None:
        with contextlib.suppress(Exception):
            await self.node._send(ws, protocol.msg(
                protocol.FLEET_ACK,
                rid=rid,
                ok=ok,
                **({"error": error} if error else {}),
                **({"info": info} if info else {}),
            ))

    async def send_action(self, target: str, action: str,
                          timeout: float | None = None, **fields) -> dict:
        """One epoch-stamped command to a peer; returns its ack payload
        (or a local error dict — callers branch on ``ok``)."""
        info = self.node.peers.get(target)
        if info is None:
            return {"ok": False, "error": f"peer {target} unknown"}
        rid = new_id("fla")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._acks[rid] = (target, info["ws"], fut)
        try:
            await self.node._send(info["ws"], protocol.msg(
                protocol.FLEET_ACTION,
                rid=rid,
                action=action,
                epoch=self.epoch,
                holder=self.node.peer_id,
                **fields,
            ))
            return await self.clock.wait_for(
                fut, timeout or self.config.ack_timeout_s
            )
        except asyncio.TimeoutError:
            return {"ok": False, "error": f"no ack from {target}"}
        except Exception as e:  # noqa: BLE001 — typed verdict, not a raise
            return {"ok": False, "error": str(e)}
        finally:
            self._acks.pop(rid, None)  # meshlint: ignore[ML-R003] -- rid-keyed ack futures: each awaiter registers and pops only its own rid

    # ---------------------------------------------------------------- tick

    async def tick(self, now: float | None = None) -> None:
        """One control-loop step. Never throws (the monitor loop hosts
        it); directly callable for deterministic tests."""
        try:
            await self._tick(self.clock.time() if now is None else now)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — the loop must outlive one bad tick
            logger.exception("fleet tick failed")

    async def _tick(self, now: float) -> None:
        if self.is_leader:
            cur = self.lease.current(now)
            if cur is not None and cur.holder != self.node.peer_id:
                self._step_down(
                    f"superseded by {cur.holder} epoch {cur.epoch}"
                )
            else:
                await self._broadcast_lease(now)
        elif self.enabled and not self.paused:
            await self._maybe_claim(now)
        _G_LEADER.set(1.0 if self.is_leader else 0.0)
        if not self.is_leader:
            return
        if self.paused:
            self._journal(now, self.D_PAUSED, "controller paused by operator", {})
            return
        digests = self.fleet_digests()
        agg = controller_aggregates(digests, serving=self.serving_peers())
        self._last_agg = agg
        _G_REPLICAS.set(float(agg.get("eligible", 0)))
        if self._action is not None:
            self._check_action_timeout(now)
            if self._action is not None:
                self._journal(
                    now, self.D_INFLIGHT,
                    f"{self._action['kind']} on {self._action.get('target')}"
                    f" ({self._action.get('phase')})",
                    agg,
                )
            return
        if self._adopt_orphans(now, agg, digests):
            return
        decision, reason, target = self._decide(now, agg, digests)
        self._journal(now, decision, reason, agg)
        if decision == self.D_SCALE_OUT:
            self._start_action("scale_out", target,
                               self._run_scale_out(target))
        elif decision == self.D_SCALE_IN:
            self._start_action("scale_in", target,
                               self._run_scale_in(target))

    # ------------------------------------------------------------ the lease

    async def _maybe_claim(self, now: float) -> None:
        lapsed = self.lease.lapsed_for(now)
        if lapsed is None:
            return
        rank = self._claim_rank()
        stagger = self.config.claim_stagger_s or self.lease.ttl_s / 3.0
        if lapsed < rank * stagger:
            return
        self.epoch = self.lease.highest_epoch + 1
        self.is_leader = True
        self.stats["takeovers"] += 1
        self.lease.observe(self._lease_frame(), now)
        self.node.recorder.incident(
            "fleet:takeover",
            detail=f"claimed lease epoch {self.epoch} "
                   f"(lapsed {lapsed:.1f}s, rank {rank})",
            node=self.node.peer_id,
        )
        await self._broadcast_lease(now)

    def _claim_rank(self) -> int:
        """This node's position among the live controller-eligible peers
        (fresh digests advertising ``fleet_controller``), sorted by peer
        id — the deterministic takeover order."""
        pids = {self.node.peer_id}
        for pid, d in self.node.health.fresh().items():
            if self._advertises_controller(d):
                pids.add(pid)
        return sorted(pids).index(self.node.peer_id)

    def _lease_frame(self, released: bool = False) -> dict:
        action = None
        if self._action is not None:
            action = {
                k: self._action.get(k)
                for k in ("kind", "target", "phase", "rid")
            }
        return protocol.msg(
            protocol.FLEET_LEASE,
            holder=self.node.peer_id,
            epoch=self.epoch,
            ttl_s=self.lease.ttl_s,
            scope=self.config.scope,
            **({"action": action} if action else {}),
            **({"released": True} if released else {}),
        )

    async def _broadcast_lease(self, now: float | None = None) -> None:
        frame = self._lease_frame()
        self.lease.observe(frame, now)  # refresh our own reign locally
        with contextlib.suppress(Exception):
            await self.node.broadcast(frame)

    def _step_down(self, why: str) -> None:
        if not self.is_leader:
            return
        self.is_leader = False
        self.stats["stepdowns"] += 1
        _G_LEADER.set(0.0)
        self._cancel_action(f"stepdown: {why}")
        self.node.recorder.incident(
            "fleet:stepdown", detail=why, node=self.node.peer_id
        )

    async def release(self) -> None:
        """Clean shutdown (node.stop): zero the TTL so followers take
        over immediately instead of waiting out the lapse."""
        if not self.is_leader:
            return
        self.is_leader = False
        self._cancel_action("node stopping")
        with contextlib.suppress(Exception):
            await self.node.broadcast(self._lease_frame(released=True))

    # ------------------------------------------------------------ decisions

    def fleet_digests(self) -> dict[str, dict]:
        """The controller's input: our own live digest plus every FRESH
        peer digest. Stale digests are already gone (HealthStore.fresh),
        so a dead node can never trigger a scale action."""
        return {
            self.node.peer_id: self.node.telemetry_digest(),
            **self.node.health.fresh(),
        }

    def serving_peers(self) -> set[str]:
        """Peers that advertise a service in scope (plus self when it
        serves locally) — the replica universe the aggregates count."""
        cfg = self.config
        out = set()
        for pid, svcs in list(self.node.providers.items()):
            for meta in list(svcs.values()):
                if _model_matches(cfg.model, meta.get("models")):
                    out.add(pid)
                    break
        for svc in list(self.node.local_services.values()):
            if _model_matches(cfg.model, svc.get_metadata().get("models")):
                out.add(self.node.peer_id)
                break
        return out

    def _decide(self, now: float, agg: dict, digests: dict):
        cfg = self.config
        eligible = int(agg.get("eligible") or 0)
        burning = int(agg.get("burning") or 0)
        fleet_burning = (
            eligible > 0
            and float(agg.get("burning_frac") or 0.0) >= cfg.burn_quorum
        )
        # pool-occupancy forecast (aggregates' pool_eta_s, derived from
        # the gossiped trend digests): projected exhaustion inside the
        # horizon is scale-out pressure NOW, not when the burn lands
        pool_eta = agg.get("pool_eta_s")
        forecast_low = (
            eligible > 0
            and cfg.pool_eta_out_s > 0
            and pool_eta is not None
            and float(pool_eta) <= cfg.pool_eta_out_s
        )
        headroom = (
            eligible > 0
            and burning == 0
            and not forecast_low
            and float(agg.get("fill_mean") or 0.0) <= cfg.headroom_fill_max
            and float(agg.get("queue_p95_max") or 0.0)
            <= cfg.headroom_queue_p95_ms
        )
        self._burn_streak = (
            self._burn_streak + 1 if (fleet_burning or forecast_low) else 0
        )
        self._headroom_streak = self._headroom_streak + 1 if headroom else 0
        # REPAIR before load-following: a crashed replica's digest goes
        # stale and simply vanishes from the aggregates — it reports no
        # burn, so the burn path alone would idle warm standbys through
        # a total outage. min_replicas is a floor to restore, not just a
        # scale-in bound; no sustain window (the capacity is already
        # gone), only the cooldown guards re-provision thrash.
        if eligible < cfg.min_replicas:
            if now - self._last_out < cfg.scale_out_cooldown_s:
                return (self.D_NOOP,
                        "below min_replicas but in scale-out cooldown", None)
            target = self.provisioner.pick_standby(digests)
            if target is None:
                return (self.D_NOOP,
                        f"eligible {eligible} below min_replicas but no "
                        "standby available", None)
            return (self.D_SCALE_OUT,
                    f"eligible {eligible} below min_replicas "
                    f"{cfg.min_replicas} — repairing", target)
        if self._burn_streak >= cfg.out_sustain_ticks:
            if eligible >= cfg.max_replicas:
                return self.D_NOOP, "scale-out pressure but at max_replicas", None
            if now - self._last_out < cfg.scale_out_cooldown_s:
                return self.D_NOOP, "scale-out pressure but in cooldown", None
            target = self.provisioner.pick_standby(digests)
            if target is None:
                return self.D_NOOP, "scale-out pressure but no standby available", None
            if fleet_burning:
                reason = (
                    f"fast-burn fleet-wide for {self._burn_streak} ticks "
                    f"({burning}/{eligible} replicas burning)"
                )
            else:
                reason = (
                    f"pool-occupancy forecast: exhaustion in ~{pool_eta}s "
                    f"on {agg.get('pool_eta_peer')} (horizon "
                    f"{cfg.pool_eta_out_s}s, {self._burn_streak} ticks)"
                )
            return self.D_SCALE_OUT, reason, target
        if self._headroom_streak >= cfg.in_sustain_ticks:
            if eligible <= cfg.min_replicas:
                return self.D_NOOP, "headroom but at min_replicas", None
            if now - self._last_in < cfg.scale_in_cooldown_s:
                return self.D_NOOP, "headroom but in scale-in cooldown", None
            target = self._pick_worst(agg, digests)
            if target is None:
                return self.D_NOOP, "headroom but no remote drain candidate", None
            return (
                self.D_SCALE_IN,
                f"headroom sustained for {self._headroom_streak} ticks",
                target,
            )
        return (
            self.D_NOOP,
            f"streaks burn={self._burn_streak}/{cfg.out_sustain_ticks} "
            f"headroom={self._headroom_streak}/{cfg.in_sustain_ticks}",
            None,
        )

    def _pick_worst(self, agg: dict, digests: dict) -> str | None:
        """The telemetry-worst REMOTE eligible replica: highest router
        penalty wins removal (the exact inverse of the routing pick, so
        scaling in removes the node traffic likes least). The controller
        never drains its own node — a leader mid-self-drain is the chaos
        case, not the steady state."""
        cands = []
        for pid in agg.get("eligible_ids") or []:
            if pid == self.node.peer_id:
                continue
            d = digests.get(pid)
            peer = self.node.peers.get(pid) or {}
            score, _ = self.node.router.score(
                {"provider_id": pid, "local": False},
                d, peer.get("rtt_ms"), 0.0, [],
            )
            cands.append((score, pid))
        if not cands:
            return None
        # worst score first; peer id breaks ties deterministically
        cands.sort(key=lambda t: (-t[0], t[1]))
        return cands[0][1]

    def _journal(self, now: float, decision: str, reason: str, agg: dict) -> None:
        entry = {
            "ts": round(now, 3),
            "leader": self.node.peer_id,
            "epoch": self.epoch,
            "decision": decision,
            "reason": reason,
            "eligible": agg.get("eligible"),
            "burning": agg.get("burning"),
            "standby": len(agg.get("standby") or []),
            "draining": len(agg.get("draining") or []),
        }
        self.decisions.append(entry)
        _C_DECISIONS.inc(decision=decision)
        self.node.recorder.record("fleet_decision", **entry)

    # -------------------------------------------------------------- actions

    def set_action_phase(self, phase: str) -> None:
        if self._action is not None:
            self._action["phase"] = phase

    def _start_action(self, kind: str, target: str | None, coro) -> None:
        self._action = {
            "kind": kind, "target": target, "phase": "starting",
            "rid": new_id("flact"), "started": self.clock.time(),
        }
        self._action_task = self.node._spawn(coro)

    def _finish_action(self, ok: bool, incident_kind: str, detail: str) -> None:
        action = self._action or {}
        now = self.clock.time()
        # ANY completed action refreshes BOTH cooldowns: a scale-out
        # immediately followed by a scale-in (or vice versa) is flapping
        # by definition
        self._last_out = now
        self._last_in = now
        self._burn_streak = 0
        self._headroom_streak = 0
        _C_ACTIONS.inc(
            kind=action.get("kind") or "unknown",
            outcome="ok" if ok else "failed",
        )
        if not ok:
            self.stats["actions_failed"] += 1
        self.node.recorder.incident(
            incident_kind, detail=detail, node=self.node.peer_id,
            extra={k: action.get(k) for k in ("kind", "target", "rid")},
        )
        self._action = None
        self._action_task = None

    def _cancel_action(self, why: str) -> None:
        if self._action_task is not None and not self._action_task.done():
            self._action_task.cancel()
        if self._action is not None:
            logger.warning("fleet action %s abandoned: %s", self._action, why)
        self._action = None
        self._action_task = None

    def _check_action_timeout(self, now: float) -> None:
        action = self._action
        if action is None:
            return
        # generous outer bound: the per-phase timeouts inside the action
        # coroutines normally finish it first — this catches a wedged task
        budget = (
            self.config.action_timeout_s
            + self.config.settle_timeout_s
            + self.config.probe_timeout_s
        )
        if now - action.get("started", now) > budget:
            # cancel the task but keep self._action until _finish_action
            # books it — the counter label and the incident extra must
            # attribute the timeout to its kind/target, not "unknown"
            if self._action_task is not None and not self._action_task.done():
                self._action_task.cancel()
            self._action_task = None
            logger.warning(
                "fleet action %s exceeded its wall-clock budget", action
            )
            self._finish_action(
                False, "fleet:action_failed",
                f"{action.get('kind')} on {action.get('target')} timed out",
            )

    async def _run_scale_out(self, target: str, adopted: bool = False) -> None:
        try:
            ok, detail = await self.provisioner.scale_out(
                target, adopted=adopted
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — a bug fails the action, typed
            ok, detail = False, f"scale-out crashed: {e!r}"
            logger.exception("scale-out crashed")
        if ok:
            self.stats["scale_out"] += 1  # meshlint: ignore[ML-R003] -- atomic counter bump: no read of stats spans an await
            self._finish_action(
                True, "fleet:scale_out",
                f"replica {target} probed and flipped eligible ({detail})",
            )
        else:
            self.stats["provision_failed"] += 1
            self._finish_action(
                False, "fleet:provision_failed",
                f"replica {target} not eligible: {detail}",
            )

    async def _run_scale_in(self, target: str, adopted: bool = False) -> None:
        try:
            cfg = self.config
            if not adopted:
                self.set_action_phase("draining")
                ack = await self.send_action(target, "drain")
                if not ack.get("ok"):
                    self._finish_action(
                        False, "fleet:action_failed",
                        f"drain of {target} refused: {ack.get('error')}",
                    )
                    return
            self.set_action_phase("awaiting_drain")
            quiet = await self._await_drained(target, cfg.action_timeout_s)
            if not quiet:
                # never strand a draining node: roll it back to eligible
                await self.send_action(target, "undrain")
                self.stats["rolled_back"] += 1
                self._finish_action(
                    False, "fleet:action_failed",
                    f"drain of {target} never quiesced; rolled back",
                )
                return
            if target not in self.node.peers:
                self.stats["scale_in"] += 1
                self._finish_action(
                    True, "fleet:scale_in", f"{target} drained and left the mesh"
                )
                return
            ack = await self.send_action(target, "to_standby")
            if ack.get("ok"):
                self.stats["scale_in"] += 1
                self._finish_action(
                    True, "fleet:scale_in",
                    f"{target} drained and converted to standby",
                )
            else:
                await self.send_action(target, "undrain")
                self.stats["rolled_back"] += 1
                self._finish_action(
                    False, "fleet:action_failed",
                    f"standby conversion of {target} failed: {ack.get('error')}",
                )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            logger.exception("scale-in crashed")
            with contextlib.suppress(Exception):
                await self.send_action(target, "undrain")
            self._finish_action(
                False, "fleet:action_failed", f"scale-in crashed: {e!r}"
            )

    async def _await_drained(self, target: str, timeout_s: float) -> bool:
        """Drain quiescence: the target's FRESH digest shows draining
        with no live rows (`engine.active_rows` zero or absent — a
        model-free node has no gauge), or the peer left the mesh."""
        deadline = self.clock.monotonic() + timeout_s
        poll = min(0.1, self.lease.ttl_s / 10.0)
        while self.clock.monotonic() < deadline:
            if target not in self.node.peers:
                return True
            d = self.node.health.fresh().get(target)
            if isinstance(d, dict) and d.get("draining"):
                rows = (d.get("gauge") or {}).get("engine.active_rows")
                if not rows:
                    return True
            await self.clock.sleep(poll)
        return False

    async def _run_rollback(self, target: str) -> None:
        try:
            ack = await self.send_action(target, "undrain")
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            ack = {"ok": False, "error": repr(e)}
        self.stats["rolled_back"] += 1
        self._finish_action(
            bool(ack.get("ok")), "fleet:drain_rollback",
            f"orphaned drain of {target} rolled back "
            f"(fleet needs capacity): {ack.get('error') or 'ok'}",
        )

    def _adopt_orphans(self, now: float, agg: dict, digests: dict) -> bool:
        """Any leader tick with no in-flight action scans for state a
        dead/partitioned predecessor left behind: a DRAINING peer (its
        scale-in died mid-flight) is adopted to completion — or rolled
        back when the fleet is burning and needs the capacity — and a
        WARMING peer (a provision died between activate and the probe)
        is re-probed to eligibility or returned to standby. This is what
        makes a controller death survivable: the state machine lives in
        the fleet's digests, not in the dead process."""
        cfg = self.config
        for pid in sorted(digests):
            if pid == self.node.peer_id:
                continue
            d = digests[pid]
            if not isinstance(d, dict):
                continue
            if d.get("draining"):
                if d.get("drain_source") != "fleet":
                    # an OPERATOR's deliberate drain (POST /admin/drain):
                    # not ours to reconcile — undraining it would reopen
                    # traffic on a node about to be killed, and adopting
                    # it would mutate fleet state the operator never
                    # asked for. The router already excludes it.
                    continue
                need_capacity = (
                    int(agg.get("burning") or 0) > 0
                    or int(agg.get("eligible") or 0) < cfg.min_replicas
                )
                if need_capacity:
                    self._journal(
                        now, self.D_ROLLBACK,
                        f"orphaned drain on {pid}: fleet needs capacity",
                        agg,
                    )
                    self._start_action(
                        "rollback", pid, self._run_rollback(pid)
                    )
                else:
                    self.stats["adopted"] += 1
                    self._journal(
                        now, self.D_ADOPT, f"adopting orphaned drain on {pid}",
                        agg,
                    )
                    self.node.recorder.incident(
                        "fleet:drain_adopted",
                        detail=f"completing predecessor's drain of {pid}",
                        node=self.node.peer_id,
                    )
                    self._start_action(
                        "scale_in", pid, self._run_scale_in(pid, adopted=True)
                    )
                return True
            if d.get("fleet_state") == "warming":
                self.stats["adopted"] += 1
                self._journal(
                    now, self.D_ADOPT,
                    f"adopting orphaned warm-up on {pid} (re-probing)",
                    agg,
                )
                self.node.recorder.incident(
                    "fleet:warmup_adopted",
                    detail=f"re-probing predecessor's half-provisioned {pid}",
                    node=self.node.peer_id,
                )
                self._start_action(
                    "scale_out", pid, self._run_scale_out(pid, adopted=True)
                )
                return True
        return False

    # ------------------------------------------------------------- override

    async def override(self, action: str, target: str | None = None) -> dict:
        """Manual override (POST /fleet/override, admin-only): pause /
        resume the loop anywhere; force a scale action on the leader —
        hysteresis is bypassed, the probe gate and one-in-flight are
        NOT."""
        now = self.clock.time()
        if action == "pause":
            self.paused = True
            self._journal(now, self.D_OVERRIDE, "paused by operator", {})
            return {"ok": True, "paused": True}
        if action == "resume":
            self.paused = False
            self._journal(now, self.D_OVERRIDE, "resumed by operator", {})
            return {"ok": True, "paused": False}
        if action not in ("scale_out", "scale_in"):
            return {"ok": False, "error": f"unknown override {action!r}"}
        if not self.is_leader:
            cur = self.lease.current(now)
            return {
                "ok": False, "error": "not_leader",
                "leader": cur.holder if cur else None,
            }
        if self._action is not None:
            return {"ok": False, "error": "action_in_flight",
                    "action": dict(self._action)}
        digests = self.fleet_digests()
        agg = controller_aggregates(digests, serving=self.serving_peers())
        if action == "scale_out":
            # an explicit target must actually BE a standby: "activate"
            # on an already-serving replica would flip it warming
            # (router-excluded mid-traffic) and a failed probe would
            # demote healthy capacity to standby
            if target is not None and (
                (digests.get(target) or {}).get("fleet_state") != "standby"
            ):
                return {"ok": False,
                        "error": f"{target} is not a fresh standby replica"}
            target = target or self.provisioner.pick_standby(digests)
            if target is None:
                return {"ok": False, "error": "no standby available"}
            self._journal(now, self.D_OVERRIDE, f"forced scale_out {target}", agg)
            self._start_action("scale_out", target, self._run_scale_out(target))
        else:
            # an explicit drain target must be an eligible remote
            # replica — draining a standby (or this node) is not a
            # scale-in, it is an outage
            if target is not None and (
                target == self.node.peer_id
                or target not in (agg.get("eligible_ids") or [])
            ):
                return {"ok": False,
                        "error": f"{target} is not a remote eligible replica"}
            target = target or self._pick_worst(agg, digests)
            if target is None:
                return {"ok": False, "error": "no remote drain candidate"}
            self._journal(now, self.D_OVERRIDE, f"forced scale_in {target}", agg)
            self._start_action("scale_in", target, self._run_scale_in(target))
        return {"ok": True, "action": dict(self._action)}

    # --------------------------------------------------------------- status

    def status(self) -> dict:
        """The ``GET /fleet`` payload."""
        now = self.clock.time()
        return {
            "node": self.node.peer_id,
            "enabled": self.enabled,
            "paused": self.paused,
            "is_leader": self.is_leader,
            "epoch": self.epoch,
            "scope": self.config.scope,
            "lease": self.lease.describe(now),
            "action": dict(self._action) if self._action else None,
            "aggregates": dict(self._last_agg),
            "decisions": list(self.decisions)[-20:],
            "stats": dict(self.stats),
            "config": asdict(self.config),
        }
