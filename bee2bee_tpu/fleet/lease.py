"""The controller lease: TTL'd, gossiped, deterministically ordered.

There is no consensus protocol here on purpose — the mesh is AP by
design (peers come and go, partitions happen), so the lease gives
*liveness with deterministic conflict resolution* rather than mutual
exclusion: during a partition both sides may elect a leader, and that is
acceptable because every replica ACTION is epoch-gated at the target
(meshnet/node.py refuses ``fleet_action`` frames from anything but the
best lease it has seen) and the healed mesh converges on exactly one
leader by ordering alone.

Ordering is total and clock-free:

- a **higher epoch always wins** (every claim bumps the highest epoch
  the claimant has observed, so a new claim supersedes a lapsed reign);
- at **equal epoch** (split-brain: two nodes claimed the same lapsed
  lease concurrently) the lexicographically **smaller holder id wins** —
  both sides compute the same winner from the two frames alone, and the
  loser steps down the moment it sees the rival frame.

Expiry never compares cross-node clocks: a lease frame carries a
*relative* ``ttl_s`` and the receiver stamps its own arrival time, the
same discipline as the health store's staleness TTL.

Takeover is staggered to avoid a thundering claim: controller-eligible
nodes (they advertise ``fleet_controller`` in their telemetry digest)
rank themselves by peer id, and rank *i* waits ``i * stagger`` past the
lapse before claiming — so the deterministic first claimant is the
smallest live peer id, and collisions (rank-0 died too) resolve by the
ordering above anyway. A keeper that has never observed ANY lease
additionally waits one full TTL of boot grace before the void counts
as a lapse, so a freshly joined node cannot usurp a live incumbent it
simply hasn't heard yet.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..clock import Clock, get_clock, resolve_clock


def lease_beats(epoch_a: int, holder_a: str, epoch_b: int, holder_b: str) -> bool:
    """True when lease (epoch_a, holder_a) wins over (epoch_b, holder_b).
    Total order: higher epoch first, then smaller holder id."""
    if epoch_a != epoch_b:
        return epoch_a > epoch_b
    return str(holder_a) < str(holder_b)


@dataclass
class LeaseView:
    """One observed (or self-issued) lease, stamped with LOCAL time."""

    holder: str
    epoch: int
    ttl_s: float
    scope: str = "default"
    action: dict | None = None  # the leader's in-flight replica action
    released: bool = False
    received_at: float = field(default_factory=lambda: get_clock().time())

    def fresh(self, now: float | None = None) -> bool:
        now = get_clock().time() if now is None else now
        return not self.released and now - self.received_at <= self.ttl_s

    def age_s(self, now: float | None = None) -> float:
        now = get_clock().time() if now is None else now
        return now - self.received_at

    def describe(self, now: float | None = None) -> dict:
        now = get_clock().time() if now is None else now
        return {
            "holder": self.holder,
            "epoch": self.epoch,
            "ttl_s": self.ttl_s,
            "scope": self.scope,
            "action": self.action,
            "released": self.released,
            "age_s": round(self.age_s(now), 3),
            "fresh": self.fresh(now),
        }


class LeaseKeeper:
    """Per-node lease bookkeeping: the best lease observed so far, the
    highest epoch ever seen (the claim floor), and the authorization
    check ``fleet_action`` targets gate on.

    Lives on EVERY node — followers and non-controllers too: any node
    may be the target of a replica action and must be able to tell the
    rightful leader from a stale or split-brain-losing one."""

    def __init__(self, ttl_s: float = 45.0, scope: str = "default",
                 clock: Clock | None = None):
        self.ttl_s = ttl_s
        self.scope = scope
        self._clock = resolve_clock(clock)
        self._view: LeaseView | None = None
        self.highest_epoch = 0
        # when the CURRENT view lapsed (or the keeper booted with none):
        # the takeover stagger counts from here. While NO lease has ever
        # been observed, lapsed_for adds one full TTL of boot grace on
        # top (see there) so a fresh node cannot claim before the
        # incumbent's gossip has had a chance to arrive.
        self._lapse_started: float = self._clock.time()
        # first-election deferral bound: set by the first
        # reset_boot_grace (node start) — see there
        self._grace_cap: float | None = None

    def reset_boot_grace(self, now: float | None = None) -> None:
        """Re-anchor the boot grace at the moment the node actually
        joins the mesh — called from P2PNode.start AND from every first
        contact with a new peer: construction→start can take longer
        than a TTL (first jit compile), and a bootstrap dial that
        stalls past one TTL after start() would otherwise silently
        consume the grace too — either way re-opening the
        fresh-joiner-usurps-live-incumbent window. No-op once any lease
        has been observed. The total deferral is CAPPED at three TTLs
        past the first anchor (node start): a rolling bootstrap — or a
        crash-looping peer minting a fresh random id per restart —
        keeps re-anchoring, and an unbounded grace would leave the
        fleet leaderless forever."""
        if self._view is not None:
            return
        now = self._clock.time() if now is None else now
        if self._grace_cap is None:
            # grace END = _lapse_started + ttl, so capping the anchor
            # at start + 2*ttl bounds the first claim to start + 3*ttl
            self._grace_cap = now + 2.0 * self.ttl_s
        self._lapse_started = min(now, self._grace_cap)

    # ------------------------------------------------------------ observe

    def observe(self, frame: dict, now: float | None = None) -> LeaseView | None:
        """Fold one FLEET_LEASE frame in; returns the resulting current
        view. A frame only replaces the held view when it wins the
        deterministic ordering, refreshes the same holder's reign, or
        the held view has lapsed (any live claim beats a dead reign)."""
        now = self._clock.time() if now is None else now
        holder = frame.get("holder")
        try:
            epoch = int(frame.get("epoch") or 0)
            ttl_s = float(frame.get("ttl_s") or self.ttl_s)
        except (TypeError, ValueError):
            return self._view
        if not holder or epoch <= 0 or ttl_s <= 0:
            return self._view
        self.highest_epoch = max(self.highest_epoch, epoch)
        action = frame.get("action")
        view = LeaseView(
            holder=str(holder), epoch=epoch, ttl_s=ttl_s,
            scope=str(frame.get("scope") or self.scope),
            action=action if isinstance(action, dict) else None,
            released=bool(frame.get("released")), received_at=now,
        )
        cur = self._view
        if (
            cur is None
            or not cur.fresh(now)
            or view.holder == cur.holder
            or lease_beats(view.epoch, view.holder, cur.epoch, cur.holder)
        ):
            self._set_view(view, now)
        return self._view

    def _set_view(self, view: LeaseView, now: float) -> None:
        self._view = view
        if view.released:
            self._lapse_started = now

    # ------------------------------------------------------------- queries

    def current(self, now: float | None = None) -> LeaseView | None:
        """The held lease when FRESH, else None (marking the lapse start
        the first time it is observed lapsed)."""
        now = self._clock.time() if now is None else now
        v = self._view
        if v is None:
            return None
        if v.fresh(now):
            return v
        # lapse start = the instant the TTL ran out, not the instant we
        # happened to look — rank-based stagger must not depend on poll
        # timing (idempotent across polls: lapse_at is a pure function
        # of the lapsed view)
        self._lapse_started = v.received_at + (0.0 if v.released else v.ttl_s)
        return None

    def lapsed_for(self, now: float | None = None) -> float | None:
        """Seconds since the lease lapsed; None while one is fresh —
        or while the BOOT GRACE runs: a keeper that has never observed
        any lease waits out one full TTL of silence before the void
        counts as a lapse. Without it a freshly booted claimant ranks
        itself on an empty view and can usurp a live incumbent (same
        epoch, smaller peer id) whose gossip simply hasn't arrived yet."""
        now = self._clock.time() if now is None else now
        if self.current(now) is not None:
            return None
        start = self._lapse_started
        if self._view is None:
            start += self.ttl_s
        return now - start if now >= start else None

    def authorizes(self, holder: str, epoch: int, now: float | None = None) -> bool:
        """May (holder, epoch) command this node right now?

        With a FRESH lease held: the recognized holder is authorized
        outright, and a rival only if it beats that reign. The all-time
        epoch floor deliberately does NOT apply here — a higher epoch
        observed once from a now-dead claimant must not permanently
        refuse the leader whose renewals we are actively accepting
        (observe() re-installs a live lower-epoch reign once the higher
        one lapses; authorization must follow the same rule).

        With NO fresh lease: the floor gates claimants — anything below
        the highest epoch ever seen is a stale controller. A node that
        has seen nothing trusts the first claimant (bootstrap: refusing
        would deadlock an empty mesh)."""
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            return False
        if not holder or epoch <= 0:
            return False
        cur = self.current(now)
        if cur is not None:
            if cur.holder == holder:
                return True
            return lease_beats(epoch, holder, cur.epoch, cur.holder)
        return epoch >= self.highest_epoch

    def describe(self, now: float | None = None) -> dict | None:
        return self._view.describe(now) if self._view is not None else None
