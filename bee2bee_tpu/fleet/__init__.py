"""Elastic fleet control loop (ROADMAP round-3 item 3).

The health plane (health.py) gossips SLO burn rates and pool/bubble
gauges, drain + live migration (meshnet/migrate.py) can empty a node
without dropping a token, and weights publish→DHT→fetch can cold-start a
replica — this package closes the loop. One controller per operator
scope, elected via a TTL'd lease gossiped as a schema-declared protocol
frame, watches the fleet aggregates and turns them into replica
lifecycle actions:

- **scale OUT** when fast-burn is fleet-wide and sustained: activate a
  standby replica (weight prefetch via the node's provision hook), run a
  warm-up generation probe, and only then flip it router-eligible — a
  half-provisioned replica never receives traffic;
- **scale IN** when headroom is sustained across the slow window: pick
  the telemetry-worst eligible node and invoke the existing
  drain+migrate path, converting the drained node back to a warm
  standby.

Every action is hysteresis-guarded (sustain windows, cooldowns, min/max
replica bounds, one in-flight action at a time), journaled to the flight
recorder as typed ``fleet:*`` incidents, and chaos-proof: a controller
death or network split never strands a draining node — the next leader
(deterministic takeover when the lease lapses) adopts or rolls back
orphaned actions. See docs/ROBUSTNESS.md "Elastic fleet control".
"""

from .controller import (
    FleetConfig,
    FleetController,
    load_fleet_config,
    parse_fleet_config,
)
from .lease import LeaseKeeper, LeaseView

__all__ = [
    "FleetConfig",
    "FleetController",
    "LeaseKeeper",
    "LeaseView",
    "load_fleet_config",
    "parse_fleet_config",
]
