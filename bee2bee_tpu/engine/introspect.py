"""Engine economics plane: retrace sentinel, HBM ledger, MFU/goodput meters,
and on-demand device profiling (ISSUE 15).

The mesh's observability so far (tracing spans, metrics histograms, the
health digest) describes *requests*. This module instruments the engine's
*execution economics* — the axes a TPU serving stack silently loses money
on:

- **RetraceSentinel** — a registry of the engine's jit roots (prefill /
  decode / penalized decode / spec-verify / CoW block copy, the pipeline
  StageRunner's stage forward). Each registered root counts its traces
  (exactly, via the jit callable's own cache size — persistent-compile-
  cache hits still count as the retrace they are) and its compile
  wall-time (attributed from ``jax.monitoring``'s backend-compile events
  while a watched call is on the stack), exposed as
  ``engine.compiles_total{root}`` / ``engine.compile_seconds{root}``.
  The **warm-up contract**: every root declares its legitimate compile
  space as a predicate over a small shape key (prefill bucket widths,
  pow2 batch buckets, pow2 block-table widths). The FIRST compile of
  each declared key — at boot or at late bucket growth — is warm-up and
  fires nothing, whenever it happens. A compile for an UNDECLARED key is
  a steady-state retrace and fires a typed ``engine:retrace_storm``
  flight-recorder incident naming the root immediately; repeated
  compiles of an already-seen key (weak-type flips, accidental cache
  invalidation) fire the same incident once they storm
  (``storm_repeats`` within ``storm_window_s``).
- **HbmLedger** — per-device live-memory breakdown from the engine's own
  buffer handles (weights / KV pool + scales / adapter pool), plus
  ``device.memory_stats()`` where the backend provides it (TPU does; CPU
  returns None): ``engine.hbm_bytes{component}`` gauges, an
  ``engine.hbm_headroom_frac`` gauge, and a workspace/other residual when
  the device total is known. The attached **PoolForecast** projects the
  paged pool's growth rate into an ``engine.pool_exhaust_eta_s`` gauge
  that feeds the admission controller's ``pool_exhausted`` shed *before*
  the free-fraction floor trips.
- **GoodputMeter** — an analytic per-model FLOPs model (matmul +
  attention terms, prefill vs decode) turns the scheduler's dispatches
  into ``engine.mfu`` (model FLOP/s over the platform peak — the honest
  utilization number, per "Scalable Training of LMs with pjit on TPUv4")
  and ``engine.goodput_tokens_per_s``, distinguishing *scheduled* token
  positions from *useful* tokens: rejected spec drafts, padded prefill
  tails, post-EOS window overshoot, failover re-prefills and migration
  re-decodes all count against goodput.
- **DeviceProfiler** — duration-bounded ``jax.profiler`` capture behind
  ``POST /debug/profile`` (api.py): artifacts zip under
  ``$BEE2BEE_INCIDENT_DIR/profiles`` and list/fetch like incidents;
  concurrent capture is refused typed.

Everything honors the telemetry never-throw contract: the sentinel,
ledger and meter must never take down a decode step. The module imports
no jax at import time (api.py imports it for the profile route).
"""

from __future__ import annotations

import io
import logging
import os
import threading
import time
import weakref
import zipfile
from collections import deque
from pathlib import Path
from typing import Callable

from ..health import get_recorder, register_digest_provider
from ..metrics import get_registry
from ..utils import new_id

logger = logging.getLogger("bee2bee_tpu.introspect")

_REG = get_registry()
# per-root compile accounting. The `root` label set is closed — it is
# exactly the roots the engine/stage-runner register at construction —
# so cardinality is bounded like every other labeled series here.
_C_COMPILES = _REG.counter(
    "engine.compiles", "jit traces per registered engine root"
)
_C_COMPILE_SECONDS = _REG.counter(
    "engine.compile_seconds", "XLA compile wall-time per registered root"
)
_C_RETRACE_STORMS = _REG.counter(
    "engine.retrace_storms",
    "steady-state retraces detected per root (undeclared shapes / "
    "repeat-key compile storms)",
)
_G_MFU = _REG.gauge(
    "engine.mfu",
    "model FLOP/s over platform peak FLOP/s, trailing window (0..1)",
)
_G_GOODPUT = _REG.gauge(
    "engine.goodput_tokens_per_s",
    "USEFUL tokens per second over the trailing window (rejected drafts, "
    "re-prefills and overshoot excluded)",
)
_G_SCHEDULED_TPS = _REG.gauge(
    "engine.scheduled_tokens_per_s",
    "token positions dispatched per second over the trailing window",
)
_G_GOODPUT_FRAC = _REG.gauge(
    "engine.goodput_fraction",
    "useful / scheduled tokens over the trailing window (0..1)",
)
_G_SPEC_ACCEPT = _REG.gauge(
    "engine.spec_acceptance",
    "cumulative accepted/drafted speculative tokens per drafter tier "
    "(tier label; absent until that tier has drafted)",
)
_G_HBM_BYTES = _REG.gauge(
    "engine.hbm_bytes", "live device memory by component (bytes)"
)
_G_HBM_HEADROOM = _REG.gauge(
    "engine.hbm_headroom_frac",
    "fraction of device memory still free (1 - in_use/limit)",
)
_G_POOL_ETA = _REG.gauge(
    "engine.pool_exhaust_eta_s",
    "projected seconds until the paged KV pool runs dry at the current "
    "growth rate (absent when the pool is not growing)",
)
_C_HOST_SYNCS = _REG.counter(
    "engine.host_syncs",
    "device->host token fetches in the decode hot loop (one per readback "
    "window — the only blocking point the overlap design permits)",
)
_C_SYNC_STALLS = _REG.counter(
    "engine.host_sync_stalls",
    "host syncs that blocked with NO other decode window in flight — the "
    "device sat idle while the host processed tokens (0 when overlap "
    "keeps the ring full)",
)
_G_OVERLAP = _REG.gauge(
    "engine.overlap_inflight",
    "decode windows still in flight on-device at readback time (0 = "
    "serialized loop, >=1 = async dispatch overlap is working)",
)

# ---------------------------------------------------------------- FLOPs model


def peak_flops_per_device(platform: str, device_kind: str = "") -> float:
    """Peak dense FLOP/s for one device, for the MFU denominator.

    ``BEE2BEE_PEAK_FLOPS`` (per device) overrides everything — the only
    honest number for exotic parts. The TPU table is bf16 peak per chip
    (public spec sheets); the CPU value is a NOMINAL placeholder so the
    gauge exists on dev boxes — CPU "MFU" is a proxy number, never a
    hardware claim (docs/OBSERVABILITY.md)."""
    env = os.environ.get("BEE2BEE_PEAK_FLOPS")
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            logger.warning("BEE2BEE_PEAK_FLOPS=%r is not a number", env)
    kind = (device_kind or "").lower()
    if platform == "tpu":
        for pat, peak in (
            ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),  # v5e/lite
            ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
        ):
            if pat in kind:
                return peak
        return 197e12  # unknown TPU: the v5e figure bench.py already uses
    if platform == "gpu":
        return 1e14  # nominal; set BEE2BEE_PEAK_FLOPS for real numbers
    return 1e11  # nominal CPU placeholder (proxy MFU only)


class FlopsModel:
    """Analytic forward-FLOPs model for one ModelConfig.

    ``flops(positions, ctx)`` = positions × (2·matmul_params +
    4·L·H·hd·ctx): the matmul term streams every (active) weight twice
    per position (multiply + add), the attention term is QKᵀ + AV
    against ``ctx`` cached positions across all query heads. Spec-verify
    and prefill positions use the same per-position formula — what
    differs between modes is how many positions the scheduler dispatches
    and what fraction turns out useful, which is exactly what the meter
    tracks separately."""

    def __init__(self, model_cfg):
        from ..models.core import matmul_params_per_token

        self.matmul_flops_per_pos = 2.0 * matmul_params_per_token(model_cfg)
        self.attn_flops_per_pos_per_ctx = (
            4.0 * model_cfg.n_layers * model_cfg.n_heads * model_cfg.head_dim
        )

    def flops(self, positions: float, ctx: float) -> float:
        return positions * (
            self.matmul_flops_per_pos
            + self.attn_flops_per_pos_per_ctx * max(ctx, 0.0)
        )


# ------------------------------------------------------------ retrace sentinel

# thread-local attribution stack for jax.monitoring compile events: the
# wrapped call pushes its root before dispatching into jax, so a compile
# fired on this thread during the call books its wall-time to that root.
_TLS = threading.local()
_LISTENER_LOCK = threading.Lock()
_LISTENER_WIRED = False
# compile seconds observed OUTSIDE any watched root (model init, eager
# ops, unwatched jits) — kept so total compile time stays accountable
_OTHER_ROOT = "other"


def _wire_monitoring_listener() -> None:
    global _LISTENER_WIRED
    with _LISTENER_LOCK:
        if _LISTENER_WIRED:
            return
        try:
            import jax.monitoring

            def _on_duration(event: str, duration: float, **_kw) -> None:
                if event != "/jax/core/compile/backend_compile_duration":
                    return
                try:
                    stack = getattr(_TLS, "stack", None)
                    root = stack[-1][1].name if stack else _OTHER_ROOT
                    _C_COMPILE_SECONDS.inc(float(duration), root=root)
                except Exception:  # noqa: BLE001 — telemetry never throws
                    pass

            jax.monitoring.register_event_duration_secs_listener(_on_duration)
            _LISTENER_WIRED = True
        except Exception:  # noqa: BLE001 — a jax without monitoring only
            # loses compile-time attribution, never serving
            logger.exception("jax.monitoring listener not wired")
            _LISTENER_WIRED = True


class _Root:
    __slots__ = (
        "name", "allowed", "seen", "traces", "last_cache_size",
        "repeat_ts", "storms", "last_storm_ts",
    )

    def __init__(self, name: str, allowed: Callable | None):
        self.name = name
        self.allowed = allowed
        self.seen: set = set()
        self.traces = 0
        self.last_cache_size = 0
        # PER-KEY repeat timestamps: a cache-flush re-warm recompiles
        # many distinct seen keys once each — only the SAME key storming
        # is the per-step-retrace signal (bounded: keys ⊆ seen)
        self.repeat_ts: dict = {}
        self.storms = 0
        self.last_storm_ts = 0.0


class RetraceSentinel:
    """Watches registered jit roots for steady-state retraces.

    One sentinel per engine/StageRunner instance: a fresh engine's boot
    compiles are that instance's warm-up, not a storm in a long-lived
    sibling. The metrics are process-global (label ``root``), so multiple
    engines in one process sum — what a /metrics consumer wants."""

    def __init__(
        self,
        node: str | None = None,
        storm_window_s: float = 60.0,
        storm_repeats: int = 3,
        recorder=None,
    ):
        self.node = node
        self.storm_window_s = float(storm_window_s)
        self.storm_repeats = int(storm_repeats)
        self._recorder = recorder
        self._lock = threading.Lock()
        self._roots: dict[str, _Root] = {}
        _wire_monitoring_listener()

    # ---- registration

    def watch(self, name: str, fn, key_fn: Callable | None = None,
              allowed: Callable | None = None):
        """Wrap a jit callable as root ``name``.

        ``key_fn(*args, **kwargs)`` maps a call to a SMALL hashable shape
        key (the registrar knows the calling convention — include
        None-flags for optional operands that select different traces);
        default: no key (every trace counts, classification limited to
        repeat-storms). ``allowed(key)`` declares the legitimate compile
        space; None accepts any first-seen key (pure growth roots)."""
        with self._lock:
            root = self._roots.get(name)
            if root is None:
                root = self._roots[name] = _Root(name, allowed)

        def wrapped(*args, **kwargs):
            stack = getattr(_TLS, "stack", None)
            if stack is None:
                stack = _TLS.stack = []
            # THIS call's cache-size baseline, read before dispatch:
            # concurrent calls through one root each compare against
            # their own baseline, so two overlapping compiles both count
            # and both classify (a shared last-size would silently drop
            # the second thread's trace — and its incident)
            try:
                sizer = getattr(fn, "_cache_size", None)
                n0 = int(sizer()) if sizer is not None else None
            except Exception:  # noqa: BLE001 — telemetry never throws
                n0 = None
            stack.append((self, root))
            try:
                return fn(*args, **kwargs)
            finally:
                stack.pop()
                self._after_call(root, fn, key_fn, args, kwargs, n0)

        wrapped.__wrapped__ = fn
        wrapped.__name__ = getattr(fn, "__name__", name)
        # capability markers (e.g. the ragged attn fn's `ragged` flag)
        # must survive the wrap — callers feature-detect off attributes
        for attr in ("ragged",):
            if hasattr(fn, attr):
                setattr(wrapped, attr, getattr(fn, attr))
        return wrapped

    # ---- classification

    def _after_call(self, root: _Root, fn, key_fn, args, kwargs,
                    n0: int | None) -> None:
        """Trace detection via the jit callable's own cache size, per
        call (grew across THIS call = this call traced): exact, and
        independent of the persistent compile cache (a disk hit skips
        XLA but still paid the trace+lowering this sentinel exists to
        catch). A cache cleared mid-call (jax.clear_caches) reads as
        n <= n0 — no count; keys stay seen so the re-compiles classify
        as repeats only if they ALSO storm. Never throws."""
        try:
            sizer = getattr(fn, "_cache_size", None)
            if sizer is None or n0 is None:
                return
            n = int(sizer())
            if n <= n0:
                return
            with self._lock:
                root.last_cache_size = n
                root.traces += 1
            _C_COMPILES.inc(root=root.name)
            key = None
            if key_fn is not None:
                try:
                    key = key_fn(*args, **kwargs)
                except Exception:  # noqa: BLE001
                    key = None
            self._classify(root, key)
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def _classify(self, root: _Root, key) -> None:
        now = time.time()
        storm_detail = None
        with self._lock:
            if key is None:
                return  # un-keyed root: counted, not classified
            if key not in root.seen:
                root.seen.add(key)
                if root.allowed is None or root.allowed(key):
                    return  # declared bucket growth / warm-up: fire nothing
                storm_detail = (
                    f"root {root.name!r} compiled an UNDECLARED shape key "
                    f"{key!r} in steady state"
                )
            else:
                # a repeat compile of a seen key: storm only when THIS
                # key storms (a single weak-type flip or a cache-flush
                # re-warm touching many keys once is noise; one key
                # retracing per step is the silent 100x killer)
                ts = root.repeat_ts.setdefault(key, deque(maxlen=32))
                ts.append(now)
                recent = [t for t in ts if now - t <= self.storm_window_s]
                if len(recent) < self.storm_repeats:
                    return
                ts.clear()
                storm_detail = (
                    f"root {root.name!r} recompiled an already-seen shape "
                    f"key {key!r} {len(recent)}x within "
                    f"{self.storm_window_s:.0f}s"
                )
            root.storms += 1
            root.last_storm_ts = now
        _C_RETRACE_STORMS.inc(root=root.name)
        try:
            rec = self._recorder or get_recorder()
            rec.incident(
                "engine:retrace_storm",
                detail=storm_detail,
                node=self.node,
                extra={
                    "root": root.name,
                    "key": repr(key),
                    "traces": root.traces,
                    "storms": root.storms,
                },
            )
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass
        logger.warning("retrace storm: %s", storm_detail)

    # ---- views

    def snapshot(self) -> dict:
        """{root: {traces, storms}} for this sentinel's roots (compile
        seconds live in the process-global counter, labeled by root)."""
        with self._lock:
            return {
                name: {"traces": r.traces, "storms": r.storms}
                for name, r in self._roots.items()
            }

    def storming(self, within_s: float | None = None) -> bool:
        horizon = within_s if within_s is not None else self.storm_window_s
        now = time.time()
        with self._lock:
            return any(
                r.last_storm_ts and now - r.last_storm_ts <= horizon
                for r in self._roots.values()
            )


# ---------------------------------------------------------------- HBM ledger


def _tree_device_bytes(tree) -> int:
    """Per-process live bytes of a pytree of (possibly sharded) arrays:
    the sum of each leaf's addressable shard buffers — replicated leaves
    count once per local device holding them, which IS the HBM truth."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        try:
            # even the attribute READ raises on a donated/deleted array —
            # a source torn down concurrently (e.g. a closed drafter)
            # must count as 0 bytes, not break the whole snapshot
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                total += sum(s.data.nbytes for s in shards)
                continue
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes:
                total += int(nbytes)
        except Exception:  # noqa: BLE001 — deleted buffers count as 0
            continue
    return total


class HbmLedger:
    """Live device-memory breakdown from registered buffer sources.

    Components register a zero-arg callable returning their live pytree
    (or None when torn down); ``snapshot()`` walks the trees, reads
    ``device.memory_stats()`` where the backend provides it, refreshes
    the ``engine.hbm_*`` gauges and returns the breakdown dict that rides
    engine.info, the telemetry digest and bench stamps."""

    def __init__(self, devices=None):
        self._lock = threading.Lock()
        self._sources: dict[str, Callable] = {}
        self._devices = devices

    def register(self, component: str, source: Callable) -> None:
        with self._lock:
            self._sources[component] = source

    def unregister(self, component: str) -> None:
        with self._lock:
            self._sources.pop(component, None)
        _G_HBM_BYTES.clear(component=component)

    def close(self) -> None:
        """Drop every source closure. The kv_pool/weights lambdas close
        over the scheduler/params — a closed engine must not keep its
        donated device buffers reachable through the ledger."""
        with self._lock:
            self._sources.clear()

    def _device_stats(self) -> tuple[int | None, int | None]:
        """(bytes_in_use, bytes_limit) across this process's devices, or
        (None, None) when the backend has no memory stats (CPU). An env
        ``BEE2BEE_HBM_BYTES`` budget substitutes for the limit so
        headroom still computes on stats-less backends."""
        import jax

        devices = self._devices
        if devices is None:
            devices = jax.local_devices()
        in_use = limit = 0
        seen = False
        for d in devices:
            try:
                st = d.memory_stats()
            except Exception:  # noqa: BLE001
                st = None
            if not st:
                continue
            seen = True
            in_use += int(st.get("bytes_in_use") or 0)
            limit += int(st.get("bytes_limit") or st.get("bytes_reservable_limit") or 0)
        if seen:
            return in_use, (limit or None)
        env = os.environ.get("BEE2BEE_HBM_BYTES")
        if env:
            try:
                return None, int(float(env))
            except ValueError:
                pass
        return None, None

    def snapshot(self) -> dict:
        """Never-throw: a ledger read must not take down a scrape."""
        try:
            return self._snapshot()
        except Exception:  # noqa: BLE001
            logger.exception("hbm ledger snapshot failed")
            return {"components": {}, "accounted_bytes": 0}

    def _snapshot(self) -> dict:
        with self._lock:
            sources = dict(self._sources)
        components: dict[str, int] = {}
        for name, src in sources.items():
            try:
                tree = src()
            except Exception:  # noqa: BLE001 — a torn-down engine reads 0
                tree = None
            components[name] = _tree_device_bytes(tree) if tree is not None else 0
        accounted = sum(components.values())
        in_use, limit = self._device_stats()
        out: dict = {
            "components": components,
            "accounted_bytes": accounted,
        }
        for name, b in components.items():
            _G_HBM_BYTES.set(b, component=name)
        if in_use is not None:
            out["bytes_in_use"] = in_use
            # XLA workspace, fragmentation, and whatever we don't track
            workspace = max(0, in_use - accounted)
            out["components"]["workspace_other"] = workspace
            _G_HBM_BYTES.set(workspace, component="workspace_other")
        else:
            _G_HBM_BYTES.clear(component="workspace_other")
        if limit:
            used = in_use if in_use is not None else accounted
            headroom = max(0.0, min(1.0, 1.0 - used / limit))
            out["bytes_limit"] = limit
            out["headroom_frac"] = round(headroom, 4)
            _G_HBM_HEADROOM.set(headroom)
        else:
            _G_HBM_HEADROOM.clear()
        return out


class PoolForecast:
    """Linear growth forecast for the paged block pool.

    The scheduler feeds ``(used, free)`` on its dispatch path (cheap:
    one deque append, self-throttled to one gauge refresh per second).
    ``eta_s()`` projects free blocks / growth rate over the trailing
    window; the admission controller sheds ``pool_exhausted`` when the
    projection undercuts its horizon — BEFORE the free-fraction floor
    trips and requests start parking on scheduler backpressure."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=256)  # (t, used, free)
        self._last_refresh = 0.0

    def feed(self, used: int, free: int, now: float | None = None) -> None:
        try:
            now = time.time() if now is None else now
            with self._lock:
                self._samples.append((now, int(used), int(free)))
                throttled = now - self._last_refresh < 1.0
                if not throttled:
                    self._last_refresh = now
            if not throttled:
                self.refresh(now)
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def eta_s(self, now: float | None = None) -> float | None:
        """Projected seconds to exhaustion, or None (shrinking pool /
        not enough signal). Needs >= 2 samples spanning >= 2 s so a
        single admission burst can't fabricate a trend."""
        now = time.time() if now is None else now
        with self._lock:
            samples = [
                s for s in self._samples if now - s[0] <= self.window_s
            ]
        if len(samples) < 2:
            return None
        t0, used0, _ = samples[0]
        t1, used1, free1 = samples[-1]
        dt = t1 - t0
        if dt < 2.0 or used1 <= used0:
            return None
        rate = (used1 - used0) / dt  # blocks/s, > 0
        return free1 / rate if free1 > 0 else 0.0

    def refresh(self, now: float | None = None) -> float | None:
        eta = self.eta_s(now)
        if eta is None:
            _G_POOL_ETA.clear()
        else:
            _G_POOL_ETA.set(eta)
        return eta


# (the admission controller reads the engine.pool_exhaust_eta_s gauge
# through router/admission.pool_exhaust_eta — the registry-read pattern
# keeps the front door free of engine imports)

# -------------------------------------------------------------- goodput meter


class GoodputMeter:
    """Scheduled-vs-useful token accounting + the MFU meter.

    ``record_dispatch(positions, ctx, scheduled)`` books compute at
    dispatch time (positions = batch rows × token width actually run,
    dead rows included — that's what the hardware computed); ``note_useful``
    books tokens that made it into a request's output. Cumulative
    counters snapshot into a bounded deque at most every 250 ms;
    ``refresh()`` derives trailing-window rates into the gauges."""

    SNAPSHOT_EVERY_S = 0.25

    def __init__(self, flops_model: FlopsModel | None, peak_flops: float,
                 window_s: float = 60.0):
        self.flops_model = flops_model
        self.peak_flops = max(float(peak_flops), 1.0)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self.scheduled_total = 0
        self.useful_total = 0
        self.flops_total = 0.0
        self._snaps: deque = deque(maxlen=512)  # (t, sched, useful, flops)
        # zero baseline: the window delta subtracts the REFERENCE
        # snapshot, so without this seed the first dispatch burst would
        # vanish from the denominator (useful > scheduled for a window)
        self._snaps.append((time.time(), 0, 0, 0.0))
        self._last_snap = 0.0
        # tier -> [drafted, accepted], cumulative. The tier label set is
        # closed (spec.TIER_LADDER), so cardinality is bounded.
        self._spec_tiers: dict[str, list] = {}

    def record_dispatch(self, positions: float, ctx: float,
                        scheduled: int) -> None:
        try:
            flops = (
                self.flops_model.flops(positions, ctx)
                if self.flops_model is not None else 0.0
            )
            with self._lock:
                self.scheduled_total += int(scheduled)
                self.flops_total += flops
            self._maybe_snap()
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def note_useful(self, n: int) -> None:
        try:
            if n <= 0:
                return
            with self._lock:
                self.useful_total += int(n)
            self._maybe_snap()
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def note_spec(self, tier: str, drafted: int, accepted: int) -> None:
        """Book one row's verify outcome against its drafter tier.

        Rejected drafts are already inside the scheduled/useful split
        (record_dispatch counts the [B,K+1] width, note_useful only the
        survivors); this adds the per-tier acceptance view on top so the
        goodput snapshot can say WHICH tier is paying for itself."""
        try:
            if drafted <= 0:
                return
            with self._lock:
                t = self._spec_tiers.setdefault(tier, [0, 0])
                t[0] += int(drafted)
                t[1] += int(accepted)
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def _maybe_snap(self, force: bool = False) -> None:
        now = time.time()
        with self._lock:
            if not force and now - self._last_snap < self.SNAPSHOT_EVERY_S:
                return
            self._last_snap = now
            self._snaps.append(
                (now, self.scheduled_total, self.useful_total, self.flops_total)
            )

    def refresh(self) -> dict:
        """Trailing-window rates -> gauges; returns the snapshot dict.
        With no dispatch inside the window the rate gauges CLEAR (the
        empty-gauge contract) — an idle engine reports nothing rather
        than its last busy reading."""
        try:
            self._maybe_snap(force=True)
            now = time.time()
            with self._lock:
                snaps = list(self._snaps)
            # the newest snapshot AT OR BEFORE the window start anchors
            # the delta (SloTracker's rule): work recorded since the
            # anchor — including the first burst over the zero baseline —
            # is inside the window
            start = now - self.window_s
            ref = snaps[0]
            for s in snaps:
                if s[0] <= start:
                    ref = s
                else:
                    break
            out: dict = {
                "scheduled_tokens_total": self.scheduled_total,
                "useful_tokens_total": self.useful_total,
                "model_flops_total": self.flops_total,
            }
            with self._lock:
                spec_tiers = {k: tuple(v) for k, v in self._spec_tiers.items()}
            if spec_tiers:
                out["spec_tiers"] = {
                    k: {"drafted": d, "accepted": a,
                        "acceptance": round(a / d, 4) if d else 0.0}
                    for k, (d, a) in spec_tiers.items()
                }
                for k, (d, a) in spec_tiers.items():
                    if d:
                        _G_SPEC_ACCEPT.set(a / d, tier=k)
            if snaps[-1][0] - ref[0] <= 0:
                for g in (_G_MFU, _G_GOODPUT, _G_SCHEDULED_TPS,
                          _G_GOODPUT_FRAC):
                    g.clear()
                return out
            t0, s0, u0, f0 = ref
            t1, s1, u1, f1 = snaps[-1]
            if (s1, u1, f1) == (s0, u0, f0):
                # nothing dispatched inside the window: the empty-gauge
                # contract (an idle engine reports nothing, not zero —
                # and never its last busy reading)
                for g in (_G_MFU, _G_GOODPUT, _G_SCHEDULED_TPS,
                          _G_GOODPUT_FRAC):
                    g.clear()
                return out
            dt = t1 - t0
            sched_rate = (s1 - s0) / dt
            useful_rate = (u1 - u0) / dt
            mfu = (f1 - f0) / dt / self.peak_flops
            out.update(
                scheduled_tokens_per_s=round(sched_rate, 3),
                goodput_tokens_per_s=round(useful_rate, 3),
                goodput_fraction=(
                    round(useful_rate / sched_rate, 4) if sched_rate > 0 else 0.0
                ),
                mfu=round(mfu, 6),
                window_s=round(dt, 3),
            )
            _G_SCHEDULED_TPS.set(sched_rate)
            _G_GOODPUT.set(useful_rate)
            _G_MFU.set(mfu)
            if sched_rate > 0:
                _G_GOODPUT_FRAC.set(useful_rate / sched_rate)
            return out
        except Exception:  # noqa: BLE001 — telemetry never throws
            logger.exception("goodput refresh failed")
            return {}


# ------------------------------------------------------------ device profiler


class ProfileInProgress(RuntimeError):
    """A capture is already running (jax.profiler is a process singleton:
    two concurrent start_trace calls corrupt each other). Typed so the
    API surface can answer 409 profile_in_progress instead of a 500."""


class DeviceProfiler:
    """Duration-bounded on-demand jax.profiler capture.

    One capture at a time per process; the artifact (the whole profile
    dir zipped into ``prof-<id>.zip``) lands under
    ``<incident_dir>/profiles`` and is listed/fetched like incident
    bundles. Capture runs on the CALLER's thread (api.py offloads via
    asyncio.to_thread) and is wall-clock bounded by ``max_duration_s``."""

    MAX_DURATION_S = 60.0

    def __init__(self, profile_dir: str | Path | None = None):
        self._dir = Path(profile_dir) if profile_dir else None
        self._lock = threading.Lock()
        self._active: dict | None = None

    @property
    def profile_dir(self) -> Path:
        if self._dir is None:
            self._dir = get_recorder().incident_dir / "profiles"
        return self._dir

    @property
    def active(self) -> dict | None:
        with self._lock:
            return dict(self._active) if self._active else None

    def capture(self, duration_s: float = 2.0,
                workload: Callable | None = None) -> dict:
        """Blocking capture: start jax.profiler, run ``workload()`` (or
        sleep) for ``duration_s``, stop, zip. Returns the artifact header.
        Raises ProfileInProgress when a capture is already running."""
        import jax

        duration_s = max(0.05, min(float(duration_s), self.MAX_DURATION_S))
        prof_id = new_id("prof")
        with self._lock:
            if self._active is not None:
                raise ProfileInProgress(
                    f"capture {self._active['id']} already running"
                )
            self._active = {"id": prof_id, "started": time.time(),
                            "duration_s": duration_s}
        raw_dir = self.profile_dir / prof_id
        try:
            raw_dir.mkdir(parents=True, exist_ok=True)
            t0 = time.time()
            jax.profiler.start_trace(str(raw_dir))
            try:
                if workload is not None:
                    while time.time() - t0 < duration_s:
                        workload()
                else:
                    time.sleep(duration_s)
            finally:
                jax.profiler.stop_trace()
            captured_s = time.time() - t0
            zip_path = self.profile_dir / f"{prof_id}.zip"
            n_files = self._zip_dir(raw_dir, zip_path)
            self._rmtree(raw_dir)
            return {
                "id": prof_id,
                "ts": t0,
                "duration_s": round(captured_s, 3),
                "files": n_files,
                "bytes": zip_path.stat().st_size,
            }
        finally:
            with self._lock:
                self._active = None

    @staticmethod
    def _zip_dir(src: Path, dst: Path) -> int:
        n = 0
        with zipfile.ZipFile(dst, "w", zipfile.ZIP_DEFLATED) as zf:
            for p in sorted(src.rglob("*")):
                if p.is_file():
                    zf.write(p, p.relative_to(src))
                    n += 1
        return n

    @staticmethod
    def _rmtree(d: Path) -> None:
        import shutil

        try:
            shutil.rmtree(d)
        except OSError:
            pass

    def list_profiles(self) -> list[dict]:
        """Newest-first artifact index (id, ts, bytes) — the GET
        /debug/profile listing; mirrors FlightRecorder.list_incidents."""
        try:
            d = self.profile_dir
            if not d.is_dir():
                return []
            out = []
            for p in sorted(d.glob("prof-*.zip"),
                            key=lambda p: p.stat().st_mtime, reverse=True):
                st = p.stat()
                out.append({
                    "id": p.stem, "ts": st.st_mtime, "bytes": st.st_size,
                })
            return out
        except Exception:  # noqa: BLE001
            logger.exception("profile listing failed")
            return []

    def profile_path(self, prof_id: str) -> Path | None:
        """Artifact path by id; None when unknown. The id is URL input —
        resolved by exact stem match, never by path join (api.py streams
        the file from this path so a multi-hundred-MB TPU capture never
        materializes in memory)."""
        try:
            d = self.profile_dir
            if not d.is_dir():
                return None
            for p in d.glob("prof-*.zip"):
                if p.stem == prof_id:
                    return p
            return None
        except Exception:  # noqa: BLE001
            logger.exception("profile lookup failed")
            return None

    def load_profile(self, prof_id: str) -> bytes | None:
        """Artifact bytes by id; None when unknown (small captures /
        tests — HTTP consumers stream via profile_path)."""
        p = self.profile_path(prof_id)
        try:
            return p.read_bytes() if p is not None else None
        except OSError:
            logger.exception("profile load failed")
            return None


_PROFILER = DeviceProfiler()


def get_profiler() -> DeviceProfiler:
    """The process-global profiler (jax.profiler is a process singleton,
    so the serializing lock must be too)."""
    return _PROFILER


# --------------------------------------------------- per-engine aggregation

# live engines' introspection blocks, keyed by id: the health digest
# provider folds every live engine into one `introspect` digest entry.
# WEAK values: an engine dropped without close() (tests churn hundreds)
# must not stay pinned here — its ledger sources hold the param arrays.
_INSTANCES_LOCK = threading.Lock()
_INSTANCES: "weakref.WeakValueDictionary[int, EngineIntrospection]" = (
    weakref.WeakValueDictionary()
)
_PROVIDER_WIRED = False


def _digest_provider() -> dict | None:
    """health.build_digest's live-path hook: refresh gauges + return the
    digest block (compiles per root, MFU/goodput, HBM headroom) for every
    live engine, merged. None when no engine runs in this process."""
    with _INSTANCES_LOCK:
        instances = list(_INSTANCES.values())
    if not instances:
        return None
    merged: dict = {"compiles": {}, "storms": 0}
    mfu = goodput = None
    hbm = None
    for ins in instances:
        snap = ins.refresh()
        for root, entry in (snap.get("compiles") or {}).items():
            slot = merged["compiles"].setdefault(
                root, {"traces": 0, "storms": 0}
            )
            slot["traces"] += entry.get("traces", 0)
            slot["storms"] += entry.get("storms", 0)
            merged["storms"] += entry.get("storms", 0)
        meter = snap.get("goodput") or {}
        if meter.get("mfu") is not None:
            mfu = (mfu or 0.0) + meter["mfu"]
        if meter.get("goodput_tokens_per_s") is not None:
            goodput = (goodput or 0.0) + meter["goodput_tokens_per_s"]
        if snap.get("hbm"):
            hbm = snap["hbm"]  # one ledger per process-backend in practice
    if mfu is not None:
        merged["mfu"] = round(mfu, 6)
    if goodput is not None:
        merged["goodput_tokens_per_s"] = round(goodput, 3)
    if hbm is not None:
        merged["hbm"] = {
            k: hbm[k]
            for k in ("accounted_bytes", "bytes_in_use", "bytes_limit",
                      "headroom_frac")
            if k in hbm
        }
    merged["storming"] = any(ins.sentinel.storming() for ins in instances)
    return merged


def _wire_provider() -> None:
    global _PROVIDER_WIRED
    if not _PROVIDER_WIRED:
        _PROVIDER_WIRED = True
        register_digest_provider("introspect", _digest_provider)


class EngineIntrospection:
    """One engine's economics instruments, built by InferenceEngine:
    the retrace sentinel its jit roots register with, the HBM ledger its
    buffer owners register with, the goodput meter the scheduler feeds,
    and the pool forecast. ``refresh()`` is the scrape/digest/bench entry
    point; ``close()`` unhooks the engine from the digest provider."""

    def __init__(self, model_cfg, mesh=None, peak_flops: float | None = None):
        platform = "cpu"
        kind = ""
        try:
            if mesh is not None:
                dev = mesh.devices.flat[0]
                platform, kind = dev.platform, dev.device_kind
            n_dev = mesh.devices.size if mesh is not None else 1
        except Exception:  # noqa: BLE001
            n_dev = 1
        if peak_flops is None:
            peak_flops = peak_flops_per_device(platform, kind) * n_dev
        self.platform = platform
        self.sentinel = RetraceSentinel()
        self.ledger = HbmLedger(
            devices=list(mesh.devices.flat) if mesh is not None else None
        )
        self.meter = GoodputMeter(FlopsModel(model_cfg), peak_flops)
        self.forecast = PoolForecast()
        with _INSTANCES_LOCK:
            _INSTANCES[id(self)] = self
        _wire_provider()

    def close(self) -> None:
        with _INSTANCES_LOCK:
            _INSTANCES.pop(id(self), None)
        # the source closures pin the scheduler's KV pool and the param
        # tree — release them with the engine
        self.ledger.close()
        # drop the economics gauges outright — with no live engine they
        # would otherwise serve this engine's last busy reading forever
        # (the empty-gauge contract; node.py's incident gauge snapshot
        # and the admission forecast shed both read them). A surviving
        # sibling engine transiently loses its series too, but every
        # scrape/digest refreshes live engines first, so the gap never
        # reaches a consumer.
        try:
            for g in (_G_MFU, _G_GOODPUT, _G_SCHEDULED_TPS,
                      _G_GOODPUT_FRAC, _G_POOL_ETA, _G_HBM_HEADROOM,
                      _G_OVERLAP):
                g.clear()
            for labels, _v in _G_HBM_BYTES.series():
                _G_HBM_BYTES.clear(**dict(labels))
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def refresh(self) -> dict:
        """Refresh every gauge this plane owns; return the snapshot that
        rides engine.info / the digest / bench ``extras.introspect``."""
        out = {
            "compiles": self.sentinel.snapshot(),
            "goodput": self.meter.refresh(),
            "hbm": self.ledger.snapshot(),
            "platform": self.platform,
            "peak_flops": self.meter.peak_flops,
        }
        # the forecast's OWN return value, not the shared process gauge:
        # with two live engines the gauge holds the last writer's number
        eta = self.forecast.refresh()
        if eta is not None:
            out["pool_exhaust_eta_s"] = round(eta, 3)
        return out


def bench_snapshot() -> dict:
    """Cumulative introspection stamp for bench rungs: per-root compile
    counters + seconds from the process registry (they survive engine
    close), plus the live engines' MFU/goodput/HBM when any still runs.
    Cheap, never throws — a bench stamp must not fail the rung."""
    try:
        out: dict = {"compiles": {}}
        compiles = _REG.get("engine.compiles")
        seconds = _REG.get("engine.compile_seconds")
        if compiles is not None:
            for labels, v in compiles.series():
                root = dict(labels).get("root", "?")
                out["compiles"].setdefault(root, {})["count"] = int(v)
        if seconds is not None:
            for labels, v in seconds.series():
                root = dict(labels).get("root", "?")
                out["compiles"].setdefault(root, {})["seconds"] = round(v, 3)
        storms = _REG.get("engine.retrace_storms")
        if storms is not None and storms.total():
            out["retrace_storms"] = storms.total()
        live = _digest_provider()
        if live:
            for k in ("mfu", "goodput_tokens_per_s", "hbm"):
                if live.get(k) is not None:
                    out[k] = live[k]
        return out
    except Exception:  # noqa: BLE001 — the stamp must not kill a rung
        return {}
