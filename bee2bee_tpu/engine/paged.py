"""Paged KV cache: block pool, free-list allocator, block-level prefix sharing.

The rectangular shared cache ``[L, bsz, max_seq, Hkv, hd]`` makes every
row — idle or short — stream its full ``max_seq`` slice through HBM each
decode step (the scheduler measured 4x decode cost at bsz=8 with one
active row). This module replaces the row-owns-capacity model with the
vLLM/"Ragged Paged Attention" (PAPERS.md, arxiv 2604.15464) pool model:

- **One pool** ``[L, num_blocks, block_size, Hkv, hd]`` holds every
  row's K/V. Block 0 is the reserved null block (padding target; never
  allocated).
- **Per-row block tables** map logical position ``p`` to pool slot
  ``(table[p // block_size], p % block_size)``. The map is
  order-preserving, so masks and position biases apply unchanged over
  the gathered view (models/core.forward's ``block_tables`` path).
- **Host-side free-list allocator with refcounts**: blocks are allocated
  lazily as decode crosses block boundaries and freed at retirement.
  Refcounts make blocks shareable — the block-level prefix cache pins a
  prompt's blocks and a matching request references the full ones
  copy-on-write (only the final partial block is ever copied, because
  the borrower will write into it from the match point).

All allocator state is host-side python/numpy owned by the scheduler
thread (single-owner rule); the only device arrays are the pool itself
and the jitted single-block copy for CoW.

Why sharing whole blocks is sound: a cache entry claims validity for
positions ``[0, n)`` of its prompt. Slots ``>= n`` in the entry's final
partial block may later receive the donor's decode tokens — but a
borrower matching ``m <= n-1`` tokens copies that partial block and only
depends on slots ``< m`` (prompt K/V, immutable once written); slots
``>= m`` are overwritten by the borrower's own prefill or causally
masked. Chunked-prefill re-anchoring can re-feed tokens below the match
point; the prefill's write floor (core.forward ``paged_write_floor``)
drops those scatter writes so shared donor blocks are strictly read-only
— recomputed K/V under a different chunk geometry is not guaranteed
bit-identical, and a rewrite would perturb co-borrowers mid-decode.
"""

from __future__ import annotations

from collections.abc import Iterable

import numpy as np

from ..metrics import get_registry

# block-pool occupancy for /metrics (one engine per serving node, so
# unlabeled gauges suffice; the last-constructed allocator owns them)
_G_BLOCKS_USED = get_registry().gauge(
    "engine.paged_blocks_in_use", "paged KV pool blocks currently referenced"
)
_G_BLOCKS_FREE = get_registry().gauge(
    "engine.paged_blocks_free", "paged KV pool blocks on the free list"
)
_G_BLOCKS_TOTAL = get_registry().gauge(
    "engine.paged_blocks_total", "paged KV pool size (incl. the null block)"
)


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def pow2_at_least(n: int) -> int:
    """Smallest power of two >= max(n, 1) — buckets the block-table width
    so the decode program compiles O(log) shapes, not one per length."""
    return 1 << max(0, (max(n, 1) - 1).bit_length())


def best_prefix_key(keys, ids) -> tuple[tuple | None, int]:
    """THE prefix-cache match scan (PagedPrefixCache, and any other
    longest-prefix lookup): the key with the longest usable prefix of ``ids``
    (usable length = min(len(key), len(ids) - 1) — the final prompt
    token always prefills so admission gets its first-sample logits; an
    entry only matches when its WHOLE usable prefix equals the prompt's).

    Element-wise with early exits: the first mismatching token abandons
    the entry, and entries that cannot beat the current best are skipped
    outright — the old form built a tuple(ids[:m]) and sliced key[:m]
    per entry per admission, O(entries * prompt_len) churn that long
    prompts paid even on guaranteed misses. Ties keep the first
    (oldest-inserted) entry, matching the old `m > best_m` scan order.
    """
    cap = len(ids) - 1
    best_key, best_m = None, 0
    for key in keys:
        m = min(len(key), cap)
        if m <= best_m:
            continue
        for i in range(m):
            if key[i] != ids[i]:
                break
        else:
            best_key, best_m = key, m
    return best_key, best_m


def prefill_chunk_positions(n: int, start: int, bucket: int, S: int) -> list[int]:
    """THE chunk walk of admission prefill: start positions of each
    [pos, pos+bucket) window covering prompt tokens [start, n), with the
    capacity re-anchor (a window that would write past S is re-anchored
    to end exactly at S — re-feeding earlier tokens rather than letting a
    clamped/dropped write corrupt K/V rows). One implementation, two
    consumers — the rectangular walk and the paged walk (whose write
    ceil drops every scatter at/past n, so the paged block-sufficiency
    precheck is simply ceil(n / block_size) no matter how the windows
    land). Terminates: each window consumes min(bucket, n - pos) >= 1 tokens
    (after a re-anchor, n <= S <= pos + bucket, so the window reaches n).
    """
    out, pos = [], start
    while True:
        if pos + bucket > S:
            pos = max(0, S - bucket)
        out.append(pos)
        pos += min(bucket, n - pos)
        if pos >= n:
            return out


class BlockAllocator:
    """Free-list + refcount allocator over pool blocks 1..num_blocks-1
    (block 0 is the reserved null block and is never handed out)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"paged pool needs >= 2 blocks, got {num_blocks}")
        self.num_blocks = num_blocks
        # pop() hands out low ids first — keeps early pool pages hot
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._refs = np.zeros((num_blocks,), np.int32)
        self.hwm = 0  # high-water mark of blocks in use (observability)
        _G_BLOCKS_TOTAL.set(num_blocks)
        self._set_gauges()

    def _set_gauges(self):
        _G_BLOCKS_USED.set(self.used_count)
        _G_BLOCKS_FREE.set(self.free_count)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_blocks - 1 - len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """n fresh blocks (refcount 1), or None when the pool can't cover
        the whole request — partial allocations would leak on the caller's
        retry path."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        self.hwm = max(self.hwm, self.used_count)
        self._set_gauges()
        return out

    def ref(self, blocks: Iterable[int]) -> None:
        for b in blocks:
            assert self._refs[b] > 0, f"ref of free block {b}"
            self._refs[b] += 1

    def deref(self, blocks: Iterable[int]) -> int:
        """Drop one reference per block; blocks reaching zero return to
        the free list. Returns how many were freed."""
        freed = 0
        for b in blocks:
            assert self._refs[b] > 0, f"deref of free block {b}"
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(b)
                freed += 1
        self._set_gauges()
        return freed

    def refcount(self, block: int) -> int:
        return int(self._refs[block])


class PagedPrefixCache:
    """Block-level prompt prefix cache: key = token-id tuple, value = the
    pool block ids covering positions [0, len(key)). Entries PIN their
    blocks via allocator refcounts — a put costs zero HBM (the deleted
    rectangular cache snapshotted a full batch-1 row per entry); the cost
    is pool blocks staying out of the free list until eviction.

    Match contract: longest usable prefix, capped at len(prompt) - 1 so
    the final token always prefills for its first-sample logits. The
    scheduler thread owns all access."""

    def __init__(self, capacity: int, allocator: BlockAllocator):
        self.capacity = capacity
        self.allocator = allocator
        # key -> tuple of block ids (insertion-ordered = LRU order)
        self._entries: dict[tuple, tuple[int, ...]] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def match(self, ids: list[int]):
        """-> (m, blocks | None): longest usable cached prefix and the
        entry's FULL block list (the caller slices per its match length)."""
        best_key, best_m = best_prefix_key(self._entries, ids)
        if best_key is None:
            return 0, None
        blocks = self._entries.pop(best_key)  # LRU touch
        self._entries[best_key] = blocks
        return best_m, blocks

    def has(self, ids: list[int]) -> bool:
        return tuple(ids) in self._entries

    def put(self, ids: list[int], blocks: Iterable[int]) -> None:
        key = tuple(ids)
        if key in self._entries:
            return
        blocks = tuple(blocks)
        self.allocator.ref(blocks)  # pin
        self._entries[key] = blocks
        while len(self._entries) > self.capacity:
            self._evict_one()

    def _evict_one(self) -> bool:
        if not self._entries:
            return False
        key = next(iter(self._entries))  # LRU = oldest insertion
        self.allocator.deref(self._entries.pop(key))
        return True

    def evict_for_pressure(self, blocks_needed: int) -> bool:
        """Free pinned blocks until the allocator can cover
        `blocks_needed`. Returns True when it can. Eviction only drops the
        CACHE's pins — blocks also referenced by an active row (or by a
        caller that pre-ref'd them for a CoW copy) survive."""
        while self.allocator.free_count < blocks_needed:
            if not self._evict_one():
                return False
        return True

    def clear(self) -> None:
        while self._evict_one():
            pass
