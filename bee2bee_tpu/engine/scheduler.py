"""Continuous-batching scheduler: shared-cache decode with rolling admission.

The round-1 engine dispatched every decode chunk of a request up front and
truncated host-side afterwards — a request stopping at 10 tokens with
max_new_tokens=2048 still paid ~2048 decode steps, and concurrent requests
were independent batch-1 programs contending for the chip. This scheduler
replaces both (the reference's torch path stops at EOS per request but has
no batching at all — reference hf.py:84-108):

- **One shared paged KV pool** plus per-row device state (current token,
  write offset). All rows decode together in one compiled program per
  chunk; on TPU, decode is HBM-bandwidth-bound on the weights, so batched
  rows ride along nearly free — this is the route to the BASELINE
  throughput ladder, not bigger single streams. The ONE cache layout is
  the block pool ``[L, Hkv, num_blocks, block_size, hd]`` + per-row block
  tables (engine/paged.py): blocks are allocated lazily, attention
  touches only live blocks — per-step cache HBM traffic scales with live
  tokens instead of ``bsz * max_seq`` (the deleted rectangular layout's
  measured 4x idle-row tax) — and batch resize/compaction are host table
  moves, zero device copies. The rect/paged mode split is GONE: dense
  attention serves the gathered block view, ``attention="flash"`` runs
  the ragged paged kernel (ops/ragged.py) straight off the pool, and
  ``attention="sp"`` shards the pool's slot dim over `seq`
  (partition.paged_cache_spec) and merges per-shard softmax partials
  over the gathered view — every combination, plus speculative decode,
  composes in a single batch.
- **Adaptive batch bucketing**: ``bsz`` tracks the active row count in
  power-of-two buckets (grow on admission, shrink on retirement, capped at
  max_batch). Each bucket size compiles the decode program once; active
  rows are kept compacted in [0, active) by host table moves into
  retirement holes.
- **Rolling admission**: new requests prefill into a private row cache
  (bucketed, compile-bounded) and are spliced into a free batch row via one
  donated dynamic_update_slice program. Admission happens between decode
  chunks; nothing waits for the batch to drain.
- **EOS early-exit**: tokens are read back every chunk; a row whose request
  hit a stop token or its token budget retires immediately and frees the
  row for the next queued request. Per-request decode cost is
  ceil(tokens_actually_generated / decode_chunk) chunks.
- **Per-row sampling** (sampling.sample_batched): temperature/top-k/top-p
  ride as [B] arrays inside the one compiled step, so mixed sampling
  settings never force a recompile.

Threading model: one daemon scheduler thread owns all device state; public
submit() only appends to a queue under a condition variable. Stream
consumers read per-request event queues (queue.Queue), so gateway threads
never touch jax state — the single-owner rule that keeps this race-free.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..health import get_recorder
from ..metrics import get_registry
from ..models import core
from ..router.fairness import WdrrQueue
from ..router.tenants import load_tenant_config
from ..tracing import get_tracer
from .introspect import _C_HOST_SYNCS, _C_SYNC_STALLS, _G_OVERLAP
from .paged import (
    BlockAllocator,
    PagedPrefixCache,
    ceil_div,
    pow2_at_least,
    prefill_chunk_positions,
)
from .sampling import sample_batched
from .spec import (
    TIER_OFF,
    DrafterStack,
    MeshDrafter,
    NgramDrafter,
    should_disable,
)

logger = logging.getLogger("bee2bee_tpu.scheduler")

# serving histograms/gauges (metrics.py): the load-bearing latency
# distributions the ROADMAP north star is judged by. Observed on the
# scheduler thread (single producer), scraped by /metrics.
_REG = get_registry()
_H_QUEUE_WAIT = _REG.histogram(
    "engine.queue_wait_ms", "submit-to-admission wait per request (ms)"
)
_H_PREFILL = _REG.histogram(
    "engine.prefill_ms",
    "admission prefill through first-token readback per request (ms)",
)
_H_STEP = _REG.histogram(
    "engine.step_ms", "one decode window / spec verify step wall time (ms)"
)
_G_BATCH_FILL = _REG.gauge(
    "engine.batch_fill", "active rows / current batch bucket (0..1)"
)
_G_ACTIVE_ROWS = _REG.gauge("engine.active_rows", "rows decoding this step")
_C_SPEC_DRAFTED = _REG.counter(
    "engine.spec_drafted", "speculative tokens proposed (tier label)"
)
_C_SPEC_ACCEPTED = _REG.counter(
    "engine.spec_accepted", "speculative tokens accepted (tier label)"
)
_C_SPEC_DEGRADED = _REG.counter(
    "engine.spec_mesh_degraded",
    "rows degraded off the mesh draft tier (reason label)",
)


@dataclass
class _Timing:
    t_submit: float = 0.0
    t_admit: float = 0.0  # popped off the queue (queue_wait endpoint)
    t_first: float = 0.0  # first token available (ttft reference point)
    t_done: float = 0.0


class Request:
    """One in-flight generation. Consumers read .events until a done event;
    the scheduler thread is the only producer."""

    def __init__(
        self,
        ids: list[int],
        max_new_tokens: int,
        temperature: float,
        top_k: int,
        top_p: float,
        stop: set[int],
        eos: int | None,
        tokenizer,
        stream: bool = False,
        repetition_penalty: float = 1.0,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        min_p: float = 0.0,
        tenant: str = "default",
        adapter: str | None = None,
    ):
        self.stream = stream
        # fairness identity (router/tenants.py): keys the scheduler's WDRR
        # submit queue, so one tenant's burst can't starve another past
        # its configured weight even below the admission layer
        self.tenant = str(tenant or "default")
        # multi-adapter serving (adapters/pool.py): which LoRA adapter
        # this row decodes under (None = the plain base model). The slot
        # resolves at ADMISSION — an adapter may page in/out while the
        # request is queued — and the acquired flag makes the pool
        # refcount release idempotent across the several retirement paths
        self.adapter = adapter or None
        self.adapter_slot = 0
        self._adapter_acquired = False
        # set by an abandoning consumer (generate_stream closed early);
        # plain bool write cross-thread — the scheduler thread reads it at
        # chunk boundaries and retires the row
        self.cancelled = False
        # live-migration import state (engine.import_generation): admission
        # takes the import path instead of prefill when set — either
        # {"offset","cur","kv"} (shipped pool blocks scatter in) or
        # {"seq","cur","kv":None} (re-prefill prompt+accepted locally)
        self.import_state: dict | None = None
        self.ids = ids
        self.max_new_tokens = max_new_tokens
        self.temperature = float(temperature if temperature is not None else 0.0)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p if top_p is not None else 1.0)
        self.min_p = float(min_p or 0.0)
        self.stop = stop
        self.eos = eos
        self.repetition_penalty = float(repetition_penalty or 1.0)
        self.presence_penalty = float(presence_penalty or 0.0)
        self.frequency_penalty = float(frequency_penalty or 0.0)
        self.tokenizer = tokenizer
        self.events: queue.Queue = queue.Queue()
        self.out_ids: list[int] = []
        self.finish: str | None = None
        self.timing = _Timing(t_submit=time.perf_counter())
        self.prompt_tokens = len(ids)
        self.bucket = 0
        self.chunks_decoded = 0  # observability: early-exit is visible here
        self._flushed_text = ""
        # speculative-decoding bookkeeping (engine/spec.py): lifetime
        # drafted/accepted/miss totals feed stats/info; the spec_tier_*
        # triple is the CURRENT tier's probe ledger — it resets on every
        # tier transition so each tier gets its own probe budget. A row
        # starts on the stack's cheapest tier (lazily, at its first
        # draft attempt) and moves through the ladder instead of dying:
        # a tier that fails its probe joins spec_tiers_failed (never
        # retried) and the row demotes/escalates via DrafterStack
        # .next_tier until the ladder is exhausted (spec_tier == "off").
        # spec_misses counts eligible steps where the tier proposed
        # nothing; each weighs like a fully-rejected K-token draft in
        # the probe math, so a tier blind to this row's content fails
        # its probe without ever drafting.
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_misses = 0
        self.spec_tier: str | None = None  # None = not yet assigned
        self.spec_tiers_failed: set = set()
        self.spec_tier_drafted = 0
        self.spec_tier_accepted = 0
        self.spec_tier_misses = 0

    # ---- token accounting (runs on the scheduler thread) ----

    def accept(self, tok: int) -> bool:
        """Feed one sampled token; returns False when the request is done
        (budget reached / stop token) — the token is NOT kept then."""
        if self.finish is not None:
            return False
        if len(self.out_ids) >= self.max_new_tokens:
            self.finish = "length"
            return False
        if tok in self.stop:
            self.finish = "eos" if tok == self.eos else "stop"
            return False
        self.out_ids.append(tok)
        if len(self.out_ids) >= self.max_new_tokens:
            self.finish = "length"  # budget exhausted by this token
        return True

    def text_delta(self, final: bool = False) -> str:
        """Cumulative-decode → UTF-8-safe incremental text (holds back a
        trailing replacement char until the multi-byte token completes)."""
        full = self.tokenizer.decode(self.out_ids)
        if not final:
            full = full.rstrip("�")
        delta = full[len(self._flushed_text):]
        self._flushed_text = full
        return delta

    @property
    def done(self) -> bool:
        return self.finish is not None

    @property
    def penalized(self) -> bool:
        """True when any occurrence penalty is active — such rows route
        through the scheduler's counts-carrying decode variant."""
        return (
            self.repetition_penalty != 1.0
            or self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
        )


@dataclass
class SchedulerStats:
    admitted: int = 0
    retired: int = 0
    chunks: int = 0  # batched decode chunks dispatched
    peak_active: int = 0
    prefix_hits: int = 0
    prefix_tokens_saved: int = 0
    # paged-pool observability. blocks_read_last_step is what the decode
    # step actually touches per layer (bsz * table-width bucket);
    # live_blocks is the sum of blocks mapped by active rows — the two
    # tracking each other is the "cache HBM reads scale with live tokens"
    # property. The deleted rectangular layout's equivalent was
    # bsz * ceil(max_seq / block_size) regardless of occupancy.
    paged_blocks_in_use: int = 0
    paged_blocks_hwm: int = 0
    paged_blocks_copied: int = 0  # CoW copies (<= 1 per prefix hit)
    paged_blocks_read_last_step: int = 0
    paged_live_blocks: int = 0
    paged_alloc_waits: int = 0  # admissions deferred on an exhausted pool
    # self-speculative decoding (engine/spec.py): one spec step = one
    # [B, K+1] verify forward replacing up to K+1 sequential decode
    # steps. acceptance (accepted/drafted) near 1 means the workload
    # repeats enough that almost every draft token was a free step;
    # near 0 means rows are paying the wider forward for nothing (the
    # per-row adaptive disable then kicks in).
    spec_steps: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    # per-tier split of the two totals above: tier name ("ngram"/"model"/
    # "mesh") -> {"drafted": n, "accepted": n}. Dashboards judge EACH
    # tier's acceptance — the model tier earning 0.6 while n-gram sits
    # at 0.05 is exactly the signal the tier ladder acts on.
    spec_tiers: dict = field(default_factory=dict)
    # decode hot loop (docs/PERF.md): windows whose dispatch carried the
    # [B, 2, V] penalty counts (fused root or split pen root alike) — the
    # "penalized rows park the whole batch on the counts window" cost is
    # exactly this counter's growth rate vs chunks
    counts_windows: int = 0
    # sticky-width growth attempts the HBM ledger's headroom gate denied
    # (the request requeues at the front and retries after retirements)
    width_grow_denials: int = 0
    # live generation migration (meshnet/migrate.py). The acceptance
    # contract of the drain path pins on these: a happy-path migration is
    # migrated_out on the source + migrated_in on the target with
    # import_reprefills UNCHANGED — zero re-prefill forwards; the
    # fallback ladder's re-prefill rung is exactly import_reprefills.
    migrated_out: int = 0       # rows checkpointed + released for export
    migrated_in: int = 0        # rows imported (KV or re-prefill)
    import_reprefills: int = 0  # imports that had to re-prefill (no KV)
    prefill_handoffs: int = 0   # disagg: rows handed off after prefill
    history: deque = field(default_factory=lambda: deque(maxlen=64))

    @property
    def spec_acceptance(self) -> float:
        return self.spec_accepted / self.spec_drafted if self.spec_drafted else 0.0


class _PoolExhausted(RuntimeError):
    """Paged block pool has no free blocks (after reclaiming prefix pins).
    Admission backpressure, not a crash — callers requeue or fail the one
    request, never the whole scheduler."""


class BatchScheduler:
    """Owns the shared cache + row table; see module docstring."""

    def __init__(self, engine, max_batch: int):
        self.engine = engine
        self.max_batch = max_batch
        self.stats = SchedulerStats()
        # submit queue with per-tenant weighted-deficit fairness
        # (router/fairness.py): deque-compatible, FIFO within a tenant,
        # WDRR across tenants — cost is the request's token budget, so a
        # 4:1-weighted tenant pair drains at ~4:1 in TOKENS under
        # saturation. Weights come from the same BEE2BEE_TENANTS config
        # the admission controller reads; with no tenants configured every
        # request shares the default queue and order stays pure FIFO.
        self._queue: WdrrQueue = WdrrQueue(
            weights={
                name: spec.weight
                for name, spec in load_tenant_config().items()
            }
        )
        self._cond = threading.Condition()
        self._shutdown = False
        # live-migration plumbing (meshnet/migrate.py). checkpoint() posts
        # (req, reply queue) pairs here; the scheduler thread services them
        # at chunk boundaries — the only moment row state is consistent.
        self._checkpoints: list[tuple[Request, queue.Queue]] = []
        # node-side hook: migrate_cb(req, snapshot, reason) -> bool, called
        # ON THE SCHEDULER THREAD when a row wants to leave (disagg
        # prefill handoff, mid-decode pool exhaustion). Returning True
        # transfers ownership of req (and its events queue) to the hook —
        # the row is released and the scheduler never touches req again.
        # The hook must be fast and thread-safe (it schedules async work).
        self.migrate_cb = None
        # disagg prefill role: freshly prefilled rows are offered to
        # migrate_cb instead of decoding locally (reason "prefill_handoff")
        self.handoff_after_prefill = False

        e = engine
        self._bsz = 1  # current batch bucket (pow2-ish, <= max_batch)
        # ONE block pool for every row + host-side tables; the pool never
        # resizes with the batch bucket (row identity lives in the block
        # table), so grow/shrink/compaction cost zero device copies and
        # per-step cache traffic follows the table width.
        self._block_size = e.engine_cfg.kv_block_size
        self._alloc = BlockAllocator(e.pool_blocks)
        self._tables = np.zeros((max_batch, e.blocks_per_row), np.int32)
        self._row_blocks: list[list[int]] = [[] for _ in range(max_batch)]
        self._cache = e.new_pool()
        # cur/offsets live as HOST numpy mirrors: every eager device op is
        # a blocking round trip on a tunneled chip (~1 s each, measured),
        # so the scheduler never runs eager jnp — host state goes in as
        # jit arguments (a cheap [B] transfer) and comes back with the
        # token readback it needed anyway
        self._cur = np.zeros((self._bsz,), np.int32)
        self._offsets = np.zeros((self._bsz,), np.int32)
        # per-row adapter slots (adapters/pool.py; 0 = base model). A host
        # mirror like _cur/_offsets: rides into the jitted step as a [B]
        # argument only when some row actually holds an adapter — the
        # all-base batch keeps the adapter-free trace (per-row gating
        # discipline, same as the penalized-counts split)
        self._aids = np.zeros((self._bsz,), np.int32)
        self._rows: list[Request | None] = [None] * self._bsz
        self._row_params_dirty = True
        self._temps = self._topps = self._topks = self._minps = None
        self._reps = self._press = self._freqs = None
        # occurrence counts [bsz, V] int32 for penalty sampling — allocated
        # lazily on the first penalized admission so the common (bench)
        # path never allocates or threads it. Rows of non-penalized
        # requests may hold stale counts; they are never read (rep=1/
        # pres=0/freq=0 rows pass through apply_penalties unchanged) and
        # every admission overwrites its row with a fresh prompt bincount.
        self._counts = None
        self._vocab = e.model_cfg.vocab_size

        # counts live [B, 2, V] (batch leading; channel 0 = prompt
        # occurrences, 1 = generated), so they get their own row helpers
        V = self._vocab

        def c_insert(c, row, b):
            return jax.lax.dynamic_update_slice(c, row, (b, 0, 0))

        def c_move(c, src, dst):
            row = jax.lax.dynamic_slice(c, (src, 0, 0), (1, 2, V))
            return jax.lax.dynamic_update_slice(c, row, (dst, 0, 0))

        # CoW single-block copy: one dim-1 slice of the pool's block dim
        # ([L, Hkv, NB, BS, hd] dim 2) copied src -> dst, donating the pool
        def copy_block(cache, src, dst):
            def cp(big):
                sizes = big.shape[:2] + (1,) + big.shape[3:]
                row = jax.lax.dynamic_slice(
                    big, (0, 0, src) + (0,) * (big.ndim - 3), sizes
                )
                return jax.lax.dynamic_update_slice(
                    big, row, (0, 0, dst) + (0,) * (big.ndim - 3)
                )

            return jax.tree.map(cp, cache)

        self._counts_zeros = jax.jit(
            lambda b: jnp.zeros((b, 2, V), jnp.int32), static_argnums=0
        )
        self._counts_grow = jax.jit(
            lambda d, s: jax.lax.dynamic_update_slice(d, s, (0, 0, 0)),
            donate_argnums=(0,),
        )
        self._counts_insert = jax.jit(c_insert, donate_argnums=(0,))
        self._counts_move = jax.jit(c_move, donate_argnums=(0,))
        self._counts_bump = jax.jit(
            lambda c, b, t: c.at[b, 1, t].add(1), donate_argnums=(0,)
        )
        self._counts_shrink = jax.jit(
            lambda c, n: c[:n], static_argnums=(1,)
        )
        # engine economics plane (engine/introspect.py): the decode roots
        # register with the engine's retrace sentinel under the declared
        # compile space — batch sizes on the pow2 grow ladder, block-table
        # widths on the pow2 width buckets. The CoW copy is scalar-arg'd
        # (one trace ever): un-predicated, repeats storm.
        ic = engine.introspect
        self._meter = ic.meter
        ic.ledger.register("kv_pool", lambda: self._cache)
        tw_ok = self._declared_table_width
        bs_ok = engine._declared_batch_sizes
        # decode hot-loop mechanisms (docs/PERF.md "Decode hot loop"):
        # resolved once from EngineConfig (env knobs already folded in by
        # its __post_init__) — the step loop branches on plain bools.
        cfg = e.engine_cfg
        self._fused = bool(cfg.fused_root)
        self._overlap = bool(cfg.decode_overlap)
        self._depth = max(1, int(cfg.readback_depth))
        self._sticky = bool(cfg.batch_sticky)
        # sticky-width idle release: an all-idle batch holds its bucket
        # this long after the last dispatch before dropping to 1 (an
        # instance attr so tests can collapse the hysteresis window)
        self._sticky_idle_s = 5.0
        self._last_dispatch_t = 0.0
        # readback ring: dispatched-but-unread decode windows. Each entry
        # carries the chained device cur/offsets, the per-chunk token
        # buffers, and its own (row, request) map — row bookkeeping may
        # drift (retirement nulls _rows[b]) between dispatch and fetch.
        self._inflight: deque = deque()
        # blocks freed by a retirement while windows were still in flight:
        # those windows keep dead-row-scattering into them, so the deref
        # waits for the ring to drain (reallocating them early would let
        # an in-flight write corrupt another row's fresh block)
        self._deferred_blocks: list[int] = []
        # (cur, offsets) shardings of the decode root's outputs, captured
        # at the first dispatch. Ring-empty dispatches re-enter the chain
        # from the numpy host mirrors, which must be committed to these
        # before the call — see the sharding note in _dispatch_window.
        self._chain_sharding: tuple | None = None
        self._decode = ic.sentinel.watch(
            "decode",
            jax.jit(self._decode_fn, donate_argnums=(2,)),
            key_fn=self._decode_key,
            allowed=lambda key: key[0] in bs_ok and tw_ok(key[1]),
        )
        if self._fused:
            # penalty counts ride the fused root (counts flag in
            # _decode_key); the split pen root never compiles
            self._decode_pen = None
        else:
            self._decode_pen = ic.sentinel.watch(
                "decode_penalized",
                jax.jit(self._decode_pen_fn, donate_argnums=(2, 4)),
                key_fn=self._decode_pen_key,
                allowed=lambda key: key[0] in bs_ok and tw_ok(key[1]),
            )
        # jitted: sample_batched run eagerly is ~15 tiny ops = ~15 round
        # trips through a tunneled chip per admission
        self._sample_first = jax.jit(sample_batched)
        self._copy_block = ic.sentinel.watch(
            "cow_copy",
            jax.jit(copy_block, donate_argnums=(0,)),
            key_fn=lambda cache, src, dst: (),
        )

        # migration block transfer (pool block dim = axis 2 of EVERY pool
        # leaf — the int8 pool's [L, Hkv, NB] scale arrays line up with
        # the [L, Hkv, NB, BS, hd] pages, so one generic gather/scatter
        # moves pages and their scales together): gather reads a row's
        # blocks out for host export (no donation — the pool keeps
        # serving), scatter writes imported blocks into freshly allocated
        # slots. Index arrays pad to pow2 widths (null block 0 / zero
        # data) so compile variants stay O(log) like the table widths;
        # pad writes land in the null block, which dead-row decode
        # scribbles on by design anyway.
        def gather_blocks(cache, idx):
            return {name: arr[:, :, idx] for name, arr in cache.items()}

        def scatter_blocks(cache, new, idx):
            return {
                name: arr.at[:, :, idx].set(new[name])
                for name, arr in cache.items()
            }

        self._gather_blocks = jax.jit(gather_blocks)
        self._scatter_blocks = jax.jit(scatter_blocks, donate_argnums=(0,))
        # int8 pool: a recycled block's scale entry must drop to zero
        # before its next tenant writes — the quantize-on-write running
        # max would otherwise inherit the PREVIOUS tenant's amax and
        # serve the new row at an inflated quantization step forever
        self._quantized = e.kv_quantized
        if self._quantized:
            def reset_scales(cache, idx):
                return dict(
                    cache,
                    k_scale=cache["k_scale"].at[:, :, idx].set(0.0),
                    v_scale=cache["v_scale"].at[:, :, idx].set(0.0),
                )

            self._reset_scales = jax.jit(reset_scales, donate_argnums=(0,))
        if e.engine_cfg.prefix_cache_entries > 0:
            self._prefix_cache = PagedPrefixCache(
                e.engine_cfg.prefix_cache_entries, self._alloc
            )
        else:
            self._prefix_cache = None

        # self-speculative decoding (engine/spec.py): greedy rows draft
        # from their own prompt+output and one [B, K+1] verify call
        # replaces up to K+1 sequential decode steps. Capability is
        # detected off the ACTIVE attention path, not the config string:
        # the verify chunk is a [B, K+1] forward through the paged write
        # path, served by dense attention over the gathered view and by
        # the ragged paged kernel alike (attn fns carrying the `ragged`
        # marker). Only 'sp' remains out — its partial-merge shard_map
        # hardcodes 1/sqrt(hd) full-causal scoring and has no paged
        # capability marker — and only then does the log fire.
        self._spec = None
        if e.engine_cfg.spec_tokens > 0:
            attn_fn = e._attn_fn()
            if not (attn_fn is None or getattr(attn_fn, "ragged", False)):
                logger.info(
                    "speculative decoding disabled: attention=%r has no "
                    "paged [B, K+1] verify capability",
                    e.engine_cfg.attention,
                )
            elif e.engine_cfg.spec_tokens + 1 >= e.max_seq_len:
                # no prompt could ever leave K+1 positions of headroom —
                # rows would never be spec-eligible; say so instead of
                # silently decoding plain forever
                logger.warning(
                    "speculative decoding disabled: spec_tokens=%d leaves "
                    "no room in max_seq_len=%d",
                    e.engine_cfg.spec_tokens, e.max_seq_len,
                )
            else:
                # the tiered drafter stack (engine/spec.py): n-gram is
                # always present as the zero-cost floor; the resident
                # model tier joins when the engine loaded one
                # (--drafter <model>); the mesh tier joins when the
                # drafter is remote (--drafter mesh) — meshnet wires its
                # transport via attach_drafter_transport. Per-row tier
                # choice + probe-driven transitions live in _spec_drafts.
                tiers = {
                    "ngram": NgramDrafter(
                        e.engine_cfg.spec_tokens,
                        e.engine_cfg.spec_min_match,
                        e.engine_cfg.spec_max_match,
                    )
                }
                if getattr(e, "drafter_model", None) is not None:
                    tiers["model"] = e.drafter_model
                if e.engine_cfg.drafter == "mesh":
                    tiers["mesh"] = MeshDrafter(
                        e.engine_cfg.spec_tokens,
                        model=getattr(e.model_cfg, "name", "") or "",
                    )
                self._spec = DrafterStack(tiers, e.engine_cfg.spec_tokens)
        self.mesh_drafter = (
            self._spec.tiers.get("mesh") if self._spec is not None else None
        )
        self._draft_tier: dict[int, str] = {}  # row -> tier that drafted

        self._thread = threading.Thread(
            target=self._loop, name="bee2bee-batch-scheduler", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ public

    def set_tenant_weights(self, weights: dict) -> None:
        """Adopt the owning node's resolved tenant weights (P2PNode
        .add_service pushes its TenantRegistry here), so a registry
        replaced at runtime can't drift from the env-seeded defaults."""
        with self._cond:
            self._queue.set_weights(weights)

    def submit(self, req: Request) -> Request:
        with self._cond:
            if self._shutdown:
                raise RuntimeError("scheduler is shut down")
            self._queue.append(
                req,
                tenant=req.tenant,
                cost=max(1.0, float(req.max_new_tokens)),
            )
            self._cond.notify()
        return req

    def checkpoint(self, req: Request, timeout: float = 30.0) -> dict | None:
        """Thread-safe: ask the scheduler thread to snapshot `req`'s state
        (prompt/output ids, sampling params, write offset, last token, and
        the referenced pool blocks as host arrays under "_kv") and RELEASE
        its row at the next chunk boundary. A still-queued request is
        pulled out of the submit queue instead (snapshot without KV).
        Returns the snapshot, or None when the request already finished —
        on a snapshot the caller owns req and its events queue from here
        (the scheduler will never emit on it again)."""
        done: queue.Queue = queue.Queue()
        with self._cond:
            if self._shutdown:
                return None
            self._checkpoints.append((req, done))
            self._cond.notify()
        try:
            return done.get(timeout=timeout)
        except queue.Empty:
            return None

    def live_requests(self) -> list[Request]:
        """Admitted + queued requests (drain enumerates these). Best-effort
        snapshot: a request may retire between this read and a
        checkpoint() — checkpoint then returns None."""
        with self._cond:
            queued = list(self._queue)
        return [r for r in self._rows if r is not None] + queued

    def shutdown(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify()
        self._thread.join(timeout=5)
        if self.mesh_drafter is not None:
            # drop the transport; the resident model tier (if any) is
            # owned by the engine and closed there
            self.mesh_drafter.close()

    @property
    def active(self) -> int:
        return sum(r is not None for r in self._rows)

    # ------------------------------------------------------------ device fns

    def _declared_table_width(self, w) -> bool:
        """Is ``w`` a legitimate block-table width for the sentinel's
        declared compile space? _table_width emits pow2 widths capped at
        blocks_per_row — anything else through a decode root is an
        undeclared shape (None = a rect/table-less call, also legal)."""
        if w is None:
            return True
        limit = self.engine.blocks_per_row
        return w == limit or (w & (w - 1) == 0 and 0 < w <= limit)

    @staticmethod
    def _decode_key(params, cur, cache, offsets, temps, topks, topps,
                    minps, key, tables=None, adapters=None, aids=None,
                    ascales=None, counts=None, reps=None, press=None,
                    freqs=None):
        """Sentinel shape key for the decode root: batch bucket, table
        width bucket, and the optional-operand None-flags (min_p, the
        adapter factors, and the fused penalty counts each select a
        distinct legitimate trace)."""
        return (
            int(cur.shape[0]),
            None if tables is None else int(tables.shape[1]),
            minps is not None, adapters is not None,
            counts is not None,
        )

    @staticmethod
    def _decode_pen_key(params, cur, cache, offsets, counts,
                        temps, topks, topps, minps, reps, press, freqs,
                        key, tables=None, adapters=None, aids=None,
                        ascales=None):
        return (
            int(cur.shape[0]),
            None if tables is None else int(tables.shape[1]),
            minps is not None, adapters is not None,
        )

    def _decode_fn(self, params, cur, cache, offsets, temps, topks, topps,
                   minps, key, tables=None, adapters=None, aids=None,
                   ascales=None, counts=None, reps=None, press=None,
                   freqs=None):
        """One chunk: decode K tokens for ALL rows. Returns
        (cur', cache', offsets', counts', toks [B, K]). `tables` [B, MBb]
        selects the paged-pool path: attention gathers only the mapped
        blocks. `adapters`/`aids`/`ascales` (adapters/pool.py) select
        per-row LoRA deltas inside the same step; None keeps the base
        trace. THE FUSED ROOT (docs/PERF.md "Decode hot loop"): when
        ``counts`` [B, 2, V] rides along, penalty application + the
        per-token occurrence bump run inside this same scan — penalized
        rows cost one extra trace (the counts None-flag in _decode_key),
        never a separate root, and rep=1/pres=0/freq=0 rows pass through
        apply_penalties unchanged, so mixed batches stay token-for-token
        identical to the split-root path. counts=None keeps the
        counts-free graph (None is a valid scan-carry pytree leaf)."""
        e = self.engine
        B = cur.shape[0]

        def step(carry, key_t):
            cur, cache, off, cnt = carry
            logits, cache = core.forward(
                params, e.model_cfg, cur[:, None], cache, off,
                attn_fn=e._attn_fn(), block_tables=tables,
                adapters=adapters, adapter_ids=aids, adapter_scales=ascales,
            )
            nxt = sample_batched(
                logits[:, -1, :], key_t, temps, topks, topps, minps,
                cnt, reps, press, freqs,
            )
            if cnt is not None:
                cnt = cnt.at[jnp.arange(B), 1, nxt].add(1)
            return (nxt, cache, off + 1, cnt), nxt

        keys = jax.random.split(key, e.engine_cfg.decode_chunk)
        (cur, cache, offsets, counts), toks = jax.lax.scan(
            step, (cur, cache, offsets, counts), keys
        )
        return cur, cache, offsets, counts, jnp.moveaxis(toks, 0, 1)

    def _decode_pen_fn(
        self, params, cur, cache, offsets, counts,
        temps, topks, topps, minps, reps, press, freqs, key, tables=None,
        adapters=None, aids=None, ascales=None,
    ):
        """Penalty-carrying decode chunk: counts ride the scan carry and
        every sampled token scatters into its row. The PRE-FUSION split
        root — registered only when fused_root is off (the parity
        reference the fused path is tested against); the fused _decode_fn
        carries counts in the same scan slot and samples with the same
        key draws, so the two are token-for-token identical."""
        e = self.engine
        B = cur.shape[0]

        def step(carry, key_t):
            cur, cache, off, counts = carry
            logits, cache = core.forward(
                params, e.model_cfg, cur[:, None], cache, off,
                attn_fn=e._attn_fn(), block_tables=tables,
                adapters=adapters, adapter_ids=aids, adapter_scales=ascales,
            )
            nxt = sample_batched(
                logits[:, -1, :], key_t, temps, topks, topps, minps,
                counts, reps, press, freqs,
            )
            counts = counts.at[jnp.arange(B), 1, nxt].add(1)
            return (nxt, cache, off + 1, counts), nxt

        keys = jax.random.split(key, e.engine_cfg.decode_chunk)
        (cur, cache, offsets, counts), toks = jax.lax.scan(
            step, (cur, cache, offsets, counts), keys
        )
        return cur, cache, offsets, counts, jnp.moveaxis(toks, 0, 1)

    # ------------------------------------------------------------ loop

    def _loop(self):
        while True:
            with self._cond:
                while (not self._queue and self.active == 0
                       and not self._checkpoints and not self._shutdown):
                    self._cond.wait()
                if self._shutdown:
                    self._fail_all("engine shut down")
                    return
            try:
                if self._inflight and (self._checkpoints or self._queue):
                    # admission and checkpoints need settled row state —
                    # drain the readback ring before touching either
                    if self._drain_inflight():
                        self._compact_and_shrink()
                self._service_checkpoints()
                self._admit()
                if self.active or self._inflight:
                    self._step()
            except Exception as e:  # noqa: BLE001 — the thread must survive:
                # a dead scheduler thread would hang every blocked caller
                logger.exception("scheduler step failed; failing active requests")
                try:
                    with self._cond:
                        self._fail_all(f"scheduler error: {e!r}")
                    self._reset_device_state()
                except Exception:
                    # recovery itself failed (dead device): stop accepting
                    # work so submit() raises instead of queueing forever
                    logger.exception("scheduler recovery failed; shutting down")
                    with self._cond:
                        self._shutdown = True
                        try:
                            self._fail_all("scheduler dead: device unrecoverable")
                        except Exception:
                            pass
                    return

    def _fail_all(self, reason: str):
        """Error-terminate every queued AND admitted request (callers are
        blocked on their event queues and must always get a done event).
        Caller must hold self._cond — submit() appends under it."""
        # abandon the readback ring outright: its device futures may be
        # poisoned, and with every row released below nobody needs them
        self._inflight.clear()
        _G_OVERLAP.set(0)
        if self._deferred_blocks:
            self._alloc.deref(self._deferred_blocks)
            self._deferred_blocks = []
        for req in list(self._queue) + [r for r in self._rows if r is not None]:
            self._release_adapter(req)
            req.finish = "error"
            req.events.put({"done": True, "result": None, "error": reason})
        self._queue.clear()
        # blocked checkpoint() callers get their None verdict too — a
        # dead scheduler must not make a drain wait out its timeout
        for _req, done in self._checkpoints:
            done.put(None)
        self._checkpoints.clear()
        for b, r in enumerate(self._rows):
            if r is not None:
                self._release_row(b)
        self._rows = [None] * self._bsz

    def _reset_device_state(self):
        """Recover to an empty bucket-1 batch after a device-side failure:
        the whole pool/allocator/prefix-pin state is rebuilt — the pool
        was donated through the failed call and may hold poisoned
        buffers."""
        self._bsz = 1
        e = self.engine
        self._inflight.clear()
        _G_OVERLAP.set(0)
        self._deferred_blocks = []  # the allocator is rebuilt below
        self._alloc = BlockAllocator(e.pool_blocks)
        self._tables[:] = 0
        self._row_blocks = [[] for _ in range(self.max_batch)]
        if self._prefix_cache is not None:
            self._prefix_cache = PagedPrefixCache(
                e.engine_cfg.prefix_cache_entries, self._alloc
            )
        self._cache = e.new_pool()
        self.stats.paged_blocks_in_use = 0
        self._cur = np.zeros((1,), np.int32)
        self._offsets = np.zeros((1,), np.int32)
        self._aids = np.zeros((1,), np.int32)
        self._rows = [None]
        self._counts = None  # lazily reallocated by the next penalized admit
        self._row_params_dirty = True

    # ------------------------------------------------------------ paged state

    def _release_row(self, b: int):
        """Drop row b's block references (shared blocks survive via their
        other refs — prefix pins, CoW donors) and null its table row so
        dead-row decode writes land in the null block."""
        if self._row_blocks[b]:
            if self._inflight:
                # in-flight windows still dead-row-scatter into these
                # blocks; deref when the ring drains (_release_deferred)
                self._deferred_blocks.extend(self._row_blocks[b])
            else:
                self._alloc.deref(self._row_blocks[b])
            self._row_blocks[b] = []
        self._tables[b, :] = 0
        self._aids[b] = 0  # dead rows gather the null adapter (zeros)
        self.stats.paged_blocks_in_use = self._alloc.used_count

    def _release_adapter(self, req: Request):
        """Return req's adapter-pool refcount (idempotent — retirement,
        migration-out and fail_all paths may all reach a request). A zero
        refcount is what lets the LRU hot-swap recycle the slot."""
        if getattr(req, "_adapter_acquired", False):
            req._adapter_acquired = False
            self.engine.adapter_pool.release(req.adapter_slot)

    def _alloc_or_evict(self, n: int) -> list[int]:
        """n fresh blocks, reclaiming LRU prefix pins under pressure;
        raises _PoolExhausted when even that can't cover it. On an int8
        pool the fresh blocks' scale entries reset to zero here — every
        allocation path (admission prefill, decode growth, CoW copy
        targets, KV imports) funnels through this method, so a new
        tenant always quantizes from a clean slate (the CoW copy and
        the import scatter then overwrite with the real scales)."""
        fresh = self._alloc.alloc(n)
        if fresh is None and self._prefix_cache is not None:
            if self._prefix_cache.evict_for_pressure(n):
                fresh = self._alloc.alloc(n)
        if fresh is None:
            raise _PoolExhausted(
                f"paged KV pool exhausted: need {n} blocks, "
                f"{self._alloc.free_count} free of {self._alloc.num_blocks}"
            )
        if self._quantized and fresh:
            # pow2-padded index (null block 0 pad) bounds compile variants
            width = pow2_at_least(len(fresh))
            idx = np.zeros((width,), np.int32)
            idx[:len(fresh)] = fresh
            self._cache = self._reset_scales(self._cache, idx)
        self.stats.paged_blocks_in_use = self._alloc.used_count
        self.stats.paged_blocks_hwm = self._alloc.hwm
        return fresh

    def _ensure_blocks(self, b: int, upto: int):
        """Grow row b's block table to cover positions [0, upto) — the
        lazy allocation that makes short rows cheap. Raises _PoolExhausted
        (with row state untouched beyond already-owned blocks)."""
        need = ceil_div(upto, self._block_size)
        have = len(self._row_blocks[b])
        if need <= have:
            return
        assert need <= self.engine.blocks_per_row, (need, upto)
        fresh = self._alloc_or_evict(need - have)
        self._row_blocks[b].extend(fresh)
        self._tables[b, have:need] = fresh

    def _table_width(self, nblocks: int) -> int:
        """Pow2-bucketed block-table width (bounds compile variants) —
        never below what any row maps, never past the physical table."""
        return min(pow2_at_least(nblocks), self.engine.blocks_per_row)

    # ------------------------------------------------------------ migration

    def _service_checkpoints(self):
        """Serve pending checkpoint() calls (scheduler thread, between
        windows — the only point rows/offsets/pool agree)."""
        with self._cond:
            if not self._checkpoints:
                return
            pending, self._checkpoints = self._checkpoints, []
        for req, done in pending:
            snap = None
            try:
                snap = self._checkpoint_one(req)
            except Exception:  # noqa: BLE001 — a failed snapshot must
                # still answer the blocked checkpoint() caller
                logger.exception("checkpoint failed")
            done.put(snap)

    def _checkpoint_one(self, req: Request) -> dict | None:
        b = next((i for i, r in enumerate(self._rows) if r is req), None)
        if b is not None:
            snap = self._snapshot_row(b, req)
            self._rows[b] = None
            self._release_row(b)
            self._release_adapter(req)  # the target re-acquires its own pin
            self._row_params_dirty = True
            self.stats.migrated_out += 1
            self._compact_and_shrink()
            return snap
        with self._cond:
            removed = self._queue.remove(req)
        if not removed:
            return None  # already retired (or unknown): nothing to move
        # still queued: no device state exists — the snapshot is metadata
        # only and imports as a plain fresh admission on the target
        return self._snapshot_meta(req)

    def _snapshot_meta(self, req: Request) -> dict:
        """The wire-portable half of a snapshot (meshnet/migrate.py ships
        it as the KV_EXPORT `gen` field; engine.import_generation rebuilds
        a Request from it). Occurrence counts are NOT here — they rebuild
        exactly from ids+out at import."""
        return {
            "v": 1,
            "model": self.engine.model_cfg.name,
            "ids": [int(t) for t in req.ids],
            "out": [int(t) for t in req.out_ids],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": req.temperature,
            "top_k": req.top_k,
            "top_p": req.top_p,
            "min_p": req.min_p,
            "repetition_penalty": req.repetition_penalty,
            "presence_penalty": req.presence_penalty,
            "frequency_penalty": req.frequency_penalty,
            "stop": sorted(int(t) for t in req.stop),
            "eos": None if req.eos is None else int(req.eos),
            "tenant": req.tenant,
            # multi-adapter serving: the target must hold (or fetch) this
            # adapter before it can resume the row — KV AND future decode
            # both depend on the adapted projections
            "adapter": req.adapter,
            "block_size": self._block_size,
            "offset": 0,
            "cur": None,
            "kv_blocks": 0,
        }

    def _snapshot_row(self, b: int, req: Request) -> dict:
        """Snapshot an ADMITTED row: metadata plus the pool blocks holding
        its live KV, read back as host arrays under "_kv" (the caller
        splits that off before the metadata rides the wire). Pure read —
        releasing the row is the caller's move. Live-row invariant:
        offset == len(ids) + len(out) - 1 and cur == out[-1] (the last
        sampled token's K/V is written by the NEXT forward), so the
        blocks covering [0, offset) are the complete recoverable state."""
        snap = self._snapshot_meta(req)
        offset = int(self._offsets[b])
        nb = ceil_div(offset, self._block_size)
        snap.update(offset=offset, cur=int(self._cur[b]), kv_blocks=nb)
        if nb:
            width = min(pow2_at_least(nb), self.engine.blocks_per_row)
            idx = np.zeros((width,), np.int32)
            idx[:nb] = self._row_blocks[b][:nb]
            got = jax.device_get(self._gather_blocks(self._cache, idx))
            # int8 pool: the per-page scales ride under their own keys
            # (k_scale/v_scale), halving the exported bytes with them
            snap["_kv"] = {
                name: np.asarray(arr[:, :, :nb]) for name, arr in got.items()
            }
        return snap

    def _paged_import(self, req: Request, b: int, st: dict):
        """Admit an IMPORTED request onto row b (engine.import_generation
        built it): either scatter its shipped KV blocks into freshly
        allocated pool slots (the happy path — zero prefill compute, the
        decode that follows is token-for-token the unmigrated rollout) or,
        KV-less, re-prefill prompt+accepted through the normal chunk walk
        (the fallback rung, counted in import_reprefills). Raises
        _PoolExhausted with the row released — imports never requeue: the
        exporting node needs a fast typed verdict to try its next rung."""
        e = self.engine
        BS = self._block_size
        kv = st.get("kv")
        try:
            if kv is not None:
                offset = int(st["offset"])
                need = ceil_div(offset, BS)
                assert need <= e.blocks_per_row, (need, offset)
                fresh = self._alloc_or_evict(need)
                self._row_blocks[b] = list(fresh)
                self._tables[b, :] = 0
                self._tables[b, :need] = fresh
                width = min(pow2_at_least(need), e.blocks_per_row)
                idx = np.zeros((width,), np.int32)
                idx[:need] = fresh
                # pad every pool leaf (pages AND int8 scales — the key
                # sets match: import_generation validated them against
                # the pool layout) to the pow2 width; pad columns target
                # the null block
                new = {}
                for name in self._cache:
                    arr = np.asarray(kv[name])
                    buf = np.zeros(
                        arr.shape[:2] + (width,) + arr.shape[3:], arr.dtype
                    )
                    buf[:, :, :need] = arr
                    new[name] = buf
                self._cache = self._scatter_blocks(self._cache, new, idx)
                self._offsets[b] = offset
                self._cur[b] = int(st["cur"])
                # prefix pins travel WITH the generation: the imported
                # prompt K/V is exactly what a local prefill would have
                # pinned, so repeat prompts hit CoW on the target too
                n = len(req.ids)
                if (self._prefix_cache is not None and offset >= n
                        and not req.adapter
                        and not self._prefix_cache.has(req.ids)):
                    self._prefix_cache.put(req.ids, fresh[:ceil_div(n, BS)])
            else:
                seq = [int(t) for t in st["seq"]]
                start, cached = (
                    self._prefix_cache.match(seq)
                    if self._prefix_cache is not None and not req.adapter
                    else (0, None)
                )
                C = e.engine_cfg.prefill_chunk
                remaining = len(seq) - (start if cached is not None else 0)
                if C is not None and remaining > C:
                    bucket = C
                else:
                    bucket = e._bucket_for(remaining)
                req.bucket = bucket
                # last_logits discarded: the next token is already known
                # (cur = out[-1]); decode resumes from it
                self._paged_prefill(req, b, bucket, start, cached, seq=seq)
                self._offsets[b] = len(seq)
                self._cur[b] = int(st["cur"])
                self.stats.import_reprefills += 1
            if req.penalized:
                if self._counts is None:
                    self._counts = self._counts_zeros(self._bsz)
                ch0 = np.bincount(
                    np.asarray(req.ids, np.int64), minlength=self._vocab
                )[:self._vocab].astype(np.int32)
                if req.out_ids:
                    ch1 = np.bincount(
                        np.asarray(req.out_ids, np.int64),
                        minlength=self._vocab,
                    )[:self._vocab].astype(np.int32)
                else:
                    ch1 = np.zeros_like(ch0)
                self._counts = self._counts_insert(
                    self._counts, np.stack([ch0, ch1])[None], np.int32(b)
                )
            self.stats.migrated_in += 1
            self.stats.paged_blocks_in_use = self._alloc.used_count
        except _PoolExhausted:
            self._release_row(b)
            raise

    # ------------------------------------------------------- batch resizing

    # minimum HBM ledger headroom fraction required to grow the batch
    # bucket (sticky widths make growth ~permanent, so a grow near the
    # memory ceiling is a standing OOM invitation, not a transient)
    _GROW_HEADROOM_MIN = 0.02

    def _growth_headroom(self) -> bool:
        """May the batch bucket grow? Gated on the HBM ledger's live
        headroom fraction (engine/introspect.py). An unknown limit
        (headroom_frac absent — e.g. CPU without BEE2BEE_HBM_BYTES)
        always allows: the gate exists to stop growth into a KNOWN
        ceiling, never to guess one."""
        try:
            frac = self.engine.introspect.ledger.snapshot().get(
                "headroom_frac"
            )
        except Exception:  # noqa: BLE001 — telemetry never blocks admission
            return True
        return frac is None or frac > self._GROW_HEADROOM_MIN

    def _resize(self, new_bsz: int):
        """Move to a new batch bucket. The pool is batch-bucket-
        independent (row identity lives in the block table), so only the
        host mirrors and the counts resize — zero cache copies. Active
        rows live in [0, active); the copy of min(old, new) leading rows
        carries them all."""
        old = self._bsz
        if new_bsz == old:
            return
        if self._counts is not None:
            if new_bsz > old:
                self._counts = self._counts_grow(
                    self._counts_zeros(new_bsz), self._counts
                )
            else:
                self._counts = self._counts_shrink(self._counts, new_bsz)
        cur = np.zeros((new_bsz,), np.int32)
        offs = np.zeros((new_bsz,), np.int32)
        aids = np.zeros((new_bsz,), np.int32)
        keep = min(old, new_bsz)
        cur[:keep] = self._cur[:keep]
        offs[:keep] = self._offsets[:keep]
        aids[:keep] = self._aids[:keep]
        self._cur = cur
        self._offsets = offs
        self._aids = aids
        self._rows = self._rows[:keep] + [None] * (new_bsz - keep)
        self._bsz = new_bsz
        self._row_params_dirty = True

    def _compact_and_shrink(self):
        """Close retirement holes by moving the highest active row down,
        then drop to a smaller bucket when occupancy allows."""
        while True:
            hole = next(
                (i for i, r in enumerate(self._rows) if r is None), None
            )
            last = next(
                (i for i in range(self._bsz - 1, -1, -1) if self._rows[i] is not None),
                None,
            )
            if hole is None or last is None or last < hole:
                break
            # compaction is a host table move — zero device copies
            self._tables[hole] = self._tables[last]
            self._tables[last] = 0
            self._row_blocks[hole] = self._row_blocks[last]
            self._row_blocks[last] = []
            if self._counts is not None:
                self._counts = self._counts_move(
                    self._counts, np.int32(last), np.int32(hole)
                )
            self._cur[hole] = self._cur[last]
            self._offsets[hole] = self._offsets[last]
            self._aids[hole] = self._aids[last]
            self._aids[last] = 0
            self._rows[hole] = self._rows[last]
            self._rows[last] = None
            self._row_params_dirty = True
        A = self.active
        if self._sticky:
            # persistent-width batches (docs/PERF.md "Decode hot loop"):
            # the batch bucket is GROW-ONLY while work flows — each bucket
            # size is a distinct decode trace, and the pow2 resize ladder's
            # shrink-then-regrow churn showed up in the compile ledger as
            # the dominant retrace source under bursty admission. A fully
            # idle batch releases the bucket only after the hysteresis
            # window, so a burst arriving right after a drain reuses the
            # already-compiled width instead of re-climbing the ladder.
            if (A == 0 and self._bsz > 1
                    and time.perf_counter() - self._last_dispatch_t
                    > self._sticky_idle_s):
                self._resize(1)
            return
        if A == 0 and self._bsz > 1:
            # the pool and prefix pins persist across idle — only the
            # host bucket shrinks (no device state to rebuild)
            self._resize(1)
        elif self._bsz > 1 and A * 2 <= self._bsz // 2:
            # quarter-occupancy hysteresis: halve without thrashing at the
            # boundary (A*2 <= bsz/2  ⇔  A <= bsz/4)
            self._resize(max(1, self._bsz // 2))

    def _paged_prefill(self, req: Request, b: int, bucket: int, start: int,
                       cached, seq: list | None = None) -> object:
        """Admit one request onto the paged pool: wire row b's block table
        (sharing a matched prefix's full blocks, CoW-copying at most its
        final partial block), chunk-prefill the remainder straight into
        the pool, and pin the prompt's blocks in the prefix cache.
        Returns last_logits [1, V]. On _PoolExhausted every reference this
        call took is released and the table row is nulled, so the caller
        can requeue the request cleanly — and the raise happens BEFORE any
        device work (block sufficiency is prechecked), so a requeue-retry
        cycle under pool pressure never redoes CoW copies or prefill
        chunks, and never double-counts prefix stats.

        ``seq`` overrides the token sequence prefilled (default: the
        prompt). The re-prefill import rung (_paged_import) passes
        prompt + accepted-so-far — one chunk walk, two consumers."""
        e = self.engine
        BS = self._block_size
        # goodput accounting: a re-prefill (migration/failover import —
        # `seq` passed) recomputes K/V the fleet already paid for once;
        # its positions are scheduled work that produces zero USEFUL
        # tokens, which is exactly how the meter is told to book it
        recompute = seq is not None
        if seq is None:
            seq = req.ids
        n = len(seq)
        if cached is None:
            start = 0
        row: list[int] = []
        self._row_blocks[b] = row
        self._tables[b, :] = 0
        temp_ref: list[int] = []
        try:
            full = start // BS
            if cached is not None:
                shared = list(cached[:full])
                # take our refs FIRST: the eviction below may reclaim
                # prefix entries — including the donor — and must not free
                # blocks this row is about to depend on
                self._alloc.ref(shared)
                row.extend(shared)
                self._tables[b, :full] = shared
                if start % BS:
                    self._alloc.ref([int(cached[full])])
                    temp_ref.append(int(cached[full]))
            # sufficiency precheck before ANY device work: the write ceil
            # drops every scatter at/past position n, so prefill claims
            # exactly the blocks covering the prompt — ceil(n / BS) —
            # regardless of bucket padding (fresh blocks = that minus the
            # shared fulls; the CoW copy target is the full-th block and
            # is counted)
            fresh_needed = ceil_div(n, BS) - full
            if fresh_needed > self._alloc.free_count and not (
                self._prefix_cache is not None
                and self._prefix_cache.evict_for_pressure(fresh_needed)
            ):
                raise _PoolExhausted(
                    f"paged KV pool exhausted: admission needs "
                    f"{fresh_needed} blocks, {self._alloc.free_count} free "
                    f"of {self._alloc.num_blocks}"
                )
            if cached is not None:
                if start % BS:
                    src = temp_ref[0]
                    fresh = self._alloc_or_evict(1)
                    # the ONE CoW device copy: the borrower writes into
                    # this block from position `start`, so it gets its own
                    self._cache = self._copy_block(
                        self._cache, np.int32(src), np.int32(fresh[0])
                    )
                    self.stats.paged_blocks_copied += 1
                    row.append(fresh[0])
                    self._tables[b, full] = fresh[0]
                    self._alloc.deref(temp_ref)
                    temp_ref.clear()
                self.stats.prefix_hits += 1
                self.stats.prefix_tokens_saved += start
            # the chunk walk (paged.prefill_chunk_positions — the
            # precheck above simulated exactly these windows). The
            # capacity re-anchor can re-feed tokens BELOW `start`;
            # recomputed K/V under a different chunk geometry is not
            # guaranteed bit-identical, so the write floor keeps shared
            # donor blocks read-only (attention still reads the donor's
            # values there)
            for pos in prefill_chunk_positions(n, start, bucket, e.max_seq_len):
                # the write ceil (n) turns the bucket's padded-tail
                # scatters into null-block writes, so the row only ever
                # claims blocks covering real prompt positions
                self._ensure_blocks(b, min(pos + bucket, n))
                chunk = seq[pos:pos + bucket]
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :len(chunk)] = chunk
                tw = self._table_width(len(row))
                tbl = np.ascontiguousarray(self._tables[b:b + 1, :tw])
                self._cache, last_logits = e._prefill(
                    e.params, tokens, self._cache,
                    np.asarray([len(chunk)], np.int32),
                    np.int32(pos), tbl, np.int32(start), np.int32(n),
                    **self._lora_args_row(req),
                )
                # economics: the bucket's padded width is what the chip
                # ran; only the real prompt tokens were useful (and none
                # on the re-prefill rung)
                self._meter.record_dispatch(
                    bucket, pos + bucket / 2.0, scheduled=bucket
                )
                if not recompute:
                    self._meter.note_useful(len(chunk))
            # adapter rows NEVER enter the prefix cache: an adapted wk/wv
            # writes adapter-specific K/V, so sharing those blocks with a
            # base-model (or other-adapter) prompt would serve silently
            # wrong attention — sharing stays base-model-only
            if (self._prefix_cache is not None and not req.adapter
                    and not self._prefix_cache.has(seq)):
                # pinning is free (refcounts, no snapshot): the entry
                # claims the blocks covering exactly the prefilled positions
                self._prefix_cache.put(seq, row[:ceil_div(n, BS)])
                # a capacity eviction inside put() may have freed blocks
                self.stats.paged_blocks_in_use = self._alloc.used_count
            return last_logits
        except _PoolExhausted:
            if temp_ref:
                self._alloc.deref(temp_ref)
            self._release_row(b)
            raise

    def _admit(self):
        """Prefill queued requests into free rows, growing the batch bucket
        up to max_batch. All prefills/inserts of an admission burst are
        dispatched asynchronously; the first tokens come back in ONE device
        sync (a sync costs ~75-100 ms through a tunneled chip — a burst of
        8 must not pay it 8 times while active streams sit undecoded)."""
        e = self.engine
        placed: list[tuple] = []  # (req, row, firsts_index)
        firsts: list = []
        while True:
            with self._cond:
                if not self._queue or self.active >= self.max_batch:
                    break
                req = self._queue.popleft()
            if req.cancelled:
                req.finish = "cancelled"
                req.timing.t_first = req.timing.t_done = time.perf_counter()
                req.events.put({"done": True, "result": e._build_result(req)})
                # the pop charged this tenant's WDRR deficit for tokens
                # that will never decode — refund, same as admission does
                # for abandoned waiters
                with self._cond:
                    self._queue.refund(
                        req.tenant, max(1.0, float(req.max_new_tokens))
                    )
                continue
            req.timing.t_admit = time.perf_counter()
            if req.adapter:
                # slot resolution happens at ADMISSION, not submit — the
                # adapter may page out while the request queues. The
                # acquire bumps the pool refcount, so a hot-swap can
                # never evict the factors under this row mid-decode.
                try:
                    req.adapter_slot = self.engine.adapter_pool.acquire(
                        req.adapter
                    )
                    req._adapter_acquired = True
                except Exception as err:  # UnknownAdapter / pool races:
                    # typed retirement — the serving surfaces map the
                    # kind onto 404 (/v1) and gen_error (p2p)
                    req.finish = "error"
                    req.events.put({
                        "done": True, "result": None,
                        "error": f"unknown adapter: {err}",
                        "error_kind": "unknown_adapter",
                    })
                    with self._cond:
                        self._queue.refund(
                            req.tenant, max(1.0, float(req.max_new_tokens))
                        )
                    continue
            if self.active == self._bsz:
                if not self._growth_headroom():
                    # HBM-ledger-gated growth (sticky widths never shrink
                    # back, so a grow under memory pressure would pin the
                    # wider bucket's footprint for good): requeue at the
                    # front — retirements free rows at the CURRENT width
                    # and the retry admits into a hole without growing
                    self._release_adapter(req)
                    with self._cond:
                        # front requeue refunds the WDRR cost charged at
                        # the pop, so the retry isn't double-billed
                        self._queue.appendleft(
                            req, tenant=req.tenant,
                            cost=max(1.0, float(req.max_new_tokens)),
                        )
                    self.stats.width_grow_denials += 1
                    break
                self._resize(min(self._bsz * 2, self.max_batch))
            b = next(i for i, r in enumerate(self._rows) if r is None)

            st = getattr(req, "import_state", None)
            if st is not None:
                # migrated-in generation (meshnet/migrate.py): no first-
                # token sample — cur is the already-emitted last token and
                # decode resumes from it on the next window
                try:
                    with get_tracer().span(
                        "engine.import", row=b,
                        offset=int(st.get("offset") or 0),
                        kv=st.get("kv") is not None,
                    ):
                        self._paged_import(req, b, st)
                except _PoolExhausted as err:
                    # typed, immediate: the exporter's fallback ladder
                    # (re-prefill elsewhere) beats parking the import on
                    # backpressure that may never clear
                    self._release_adapter(req)
                    req.finish = "error"
                    req.events.put({
                        "done": True, "result": None,
                        "error": f"import failed: {err}",
                        "error_kind": "pool_exhausted",
                    })
                    # the pop charged this tenant's WDRR deficit for
                    # tokens that will never decode — refund, same as the
                    # cancelled path above
                    with self._cond:
                        self._queue.refund(
                            req.tenant, max(1.0, float(req.max_new_tokens))
                        )
                    continue
                except Exception as err:
                    # this request is in neither _queue nor _rows, so the
                    # _fail_all sweep upstream can never release its slot
                    # lease — drop it here or the refcount pins the slot
                    # (and eventually the whole pool) until restart
                    self._release_adapter(req)
                    req.finish = "error"
                    req.events.put({
                        "done": True, "result": None,
                        "error": f"import failed: {err!r}",
                    })
                    raise
                self._rows[b] = req
                self._aids[b] = req.adapter_slot
                req.timing.t_first = time.perf_counter()
                self.stats.admitted += 1
                self._row_params_dirty = True
                self.stats.peak_active = max(self.stats.peak_active, self.active)
                # the import verdict the serving node's ACK rides on
                req.events.put({"imported": True})
                continue

            n = len(req.ids)
            # longest cached prompt prefix: admit from there and prefill
            # only the remainder (chat transcripts grow by appending).
            # Adapter rows skip the cache both ways — their K/V diverges
            # from the base model's under the adapted projections
            start, cached = (
                self._prefix_cache.match(req.ids)
                if self._prefix_cache is not None and not req.adapter
                else (0, None)
            )
            C = e.engine_cfg.prefill_chunk
            remaining = n - (start if cached is not None else 0)
            if C is not None and remaining > C:
                bucket = C  # chunked: one compiled shape for all lengths
            else:
                bucket = e._bucket_for(remaining)
            req.bucket = bucket
            try:
                with get_tracer().span(
                    "engine.admit", row=b, prompt_tokens=n, bucket=bucket,
                    prefix=start,
                ):
                    # np arguments throughout: jit converts them on entry
                    # (one small transfer), no eager ops, no blocking.
                    # Prefill straight into the shared pool through the
                    # row's block table; prefix hits share the donor's
                    # full blocks CoW (engine/paged.py)
                    last_logits = self._paged_prefill(
                        req, b, bucket, start, cached
                    )
                    # one arg tuple for plain and penalized rows: a
                    # marshalling change must hit both identically
                    sample_args = [
                        last_logits,
                        e._next_key(),
                        np.asarray([req.temperature], np.float32),
                        np.asarray([req.top_k], np.int32),
                        np.asarray([req.top_p], np.float32),
                        (np.asarray([req.min_p], np.float32)
                         if req.min_p > 0 else None),
                    ]
                    if req.penalized:
                        # prompt occurrences host-side (bincount is O(n+V)
                        # in numpy — no device round trip), shipped as the
                        # row's fresh counts; the first sample sees them.
                        # Channel 0: prompt (repetition's "seen"); channel
                        # 1: generated, fresh at zero (presence/frequency)
                        if self._counts is None:
                            self._counts = self._counts_zeros(self._bsz)
                        prompt_counts = np.bincount(
                            np.asarray(req.ids, np.int64), minlength=self._vocab
                        )[:self._vocab].astype(np.int32)
                        row_counts = np.stack(
                            [prompt_counts, np.zeros_like(prompt_counts)]
                        )[None]
                        self._counts = self._counts_insert(
                            self._counts, row_counts, np.int32(b)
                        )
                        sample_args += [
                            row_counts,
                            np.asarray([req.repetition_penalty], np.float32),
                            np.asarray([req.presence_penalty], np.float32),
                            np.asarray([req.frequency_penalty], np.float32),
                        ]
                    first = self._sample_first(*sample_args)
            except _PoolExhausted as err:
                # backpressure, not failure: _paged_prefill released the
                # row's blocks before raising. With work in flight (or a
                # burst just placed) blocks WILL free — requeue at the
                # front and admit again after the next window. With
                # nothing in flight and nothing left to evict, this
                # request can never fit the configured pool: fail it.
                # either way this admission attempt is over: return the
                # adapter refcount (a requeued retry re-acquires)
                self._release_adapter(req)
                if self.active > 0 or placed:
                    with self._cond:
                        # front requeue refunds the WDRR cost charged at
                        # the pop, so the retry isn't double-billed
                        self._queue.appendleft(
                            req, tenant=req.tenant,
                            cost=max(1.0, float(req.max_new_tokens)),
                        )
                    self.stats.paged_alloc_waits += 1
                    break
                req.finish = "error"
                req.events.put({
                    "done": True, "result": None,
                    "error": f"admission failed: {err} "
                             "(kv_pool_blocks too small for this request)",
                })
                # TERMINAL exhaustion (nothing in flight to free blocks) is
                # an incident, unlike the backpressure requeue above — a
                # pool sized under the workload is an operator problem the
                # flight recorder should evidence
                get_recorder().incident(
                    "pool_exhausted",
                    detail=str(err),
                    extra={"prompt_tokens": len(req.ids)},
                )
                continue
            except Exception as err:
                # the popped request is in neither _queue nor _rows: fail it
                # here or its caller hangs; then let _loop's handler recover
                # (which errors the rest of this burst — they sit in _rows)
                self._release_adapter(req)
                req.finish = "error"
                req.events.put(
                    {"done": True, "result": None, "error": f"admission failed: {err!r}"}
                )
                raise
            # reserve the row now (cur gets the real token after readback)
            self._rows[b] = req
            self._offsets[b] = n
            self._aids[b] = req.adapter_slot
            placed.append((req, b, len(firsts)))
            firsts.append(first)

        if not placed:
            return
        # ONE blocking gather for the whole burst (device_get on the list
        # fetches all; no eager concatenate op on device)
        toks = np.concatenate([np.asarray(x) for x in jax.device_get(firsts)])
        now = time.perf_counter()
        for req, b, i in placed:
            tok = int(toks[i])
            req.timing.t_first = now
            t = req.timing
            _H_QUEUE_WAIT.observe((t.t_admit - t.t_submit) * 1000.0)
            _H_PREFILL.observe((now - t.t_admit) * 1000.0)
            self.stats.admitted += 1
            accepted = req.accept(tok)
            if accepted:
                # the admission-sampled first token is as useful as any
                # decode-window token — and its slot must be SCHEDULED
                # too (its FLOPs were booked with the prefill positions;
                # without the slot, a bucket-exact prompt could push
                # useful past scheduled and the 0..1 fraction past 1)
                self._meter.record_dispatch(0.0, 0.0, scheduled=1)
                self._meter.note_useful(1)
            if accepted and req.stream:
                # token events (and their cumulative re-decode) are only
                # for streaming consumers; generate() reads the done event
                req.events.put(
                    {"token": tok, "tokens": [tok], "text": req.text_delta(final=req.done)}
                )
            if req.done:  # instant stop/zero-budget: free the row again
                self._rows[b] = None
                self._release_row(b)
                self._retire(req)
                continue
            if req.penalized and self._counts is not None:
                # the first token was sampled AFTER the prompt bincount
                # shipped; it must count toward later penalties too
                self._counts = self._counts_bump(
                    self._counts, np.int32(b), np.int32(tok)
                )
            self._cur[b] = tok
            self._row_params_dirty = True
            self.stats.peak_active = max(self.stats.peak_active, self.active)
        # disaggregated prefill→decode: a prefill-designated node offers
        # every freshly prefilled row to the migration hook; an accepted
        # row ships its prompt KV to a decode peer and never decodes here
        # (the hook owns req from the True return on). TTFT stays local —
        # the first token was sampled above — so the existing histograms
        # measure the handoff regime unchanged.
        if self.handoff_after_prefill and self.migrate_cb is not None:
            for req, b, _i in placed:
                if self._rows[b] is not req or req.done or req.cancelled:
                    continue
                if req.max_new_tokens - len(req.out_ids) < 2:
                    continue  # nothing left worth shipping
                try:
                    snap = self._snapshot_row(b, req)
                    accepted = bool(
                        self.migrate_cb(req, snap, "prefill_handoff")
                    )
                except Exception:  # noqa: BLE001 — keep decoding locally
                    logger.exception("prefill handoff failed")
                    continue
                if accepted:
                    self._rows[b] = None
                    self._release_row(b)
                    self._release_adapter(req)
                    self._row_params_dirty = True
                    self.stats.migrated_out += 1
                    self.stats.prefill_handoffs += 1
        self._compact_and_shrink()

    def _row_sampling_arrays(self):
        if self._row_params_dirty or self._temps is None:
            temps = [r.temperature if r else 0.0 for r in self._rows]
            topks = [r.top_k if r else 0 for r in self._rows]
            topps = [r.top_p if r else 1.0 for r in self._rows]
            # host np: uploaded as jit args, never eager device arrays
            self._temps = np.asarray(temps, np.float32)
            self._topks = np.asarray(topks, np.int32)
            self._topps = np.asarray(topps, np.float32)
            self._minps = np.asarray(
                [r.min_p if r else 0.0 for r in self._rows], np.float32
            )
            self._reps = np.asarray(
                [r.repetition_penalty if r else 1.0 for r in self._rows],
                np.float32,
            )
            self._press = np.asarray(
                [r.presence_penalty if r else 0.0 for r in self._rows],
                np.float32,
            )
            self._freqs = np.asarray(
                [r.frequency_penalty if r else 0.0 for r in self._rows],
                np.float32,
            )
            self._row_params_dirty = False
        return self._temps, self._topks, self._topps

    def _lora_args(self) -> dict:
        """Adapter kwargs for the batch-wide jitted calls (decode window /
        spec verify): EMPTY when no active row holds an adapter, so the
        all-base batch runs the unchanged adapter-free trace — the same
        batch-level gate the penalized-counts split uses. Otherwise the
        pool's stacked factors + the [bsz] per-row slot ids (null slot 0
        for base rows in the mixed batch)."""
        pool = self.engine.adapter_pool
        if pool is None or not self._aids.any():
            return {}
        adapters, scales = pool.device_args()
        return {"adapters": adapters, "aids": self._aids, "ascales": scales}

    def _lora_args_row(self, req: Request) -> dict:
        """Adapter kwargs for ONE row's prefill calls."""
        if not getattr(req, "_adapter_acquired", False):
            return {}
        adapters, scales = self.engine.adapter_pool.device_args()
        return {
            "adapters": adapters,
            "aids": np.asarray([req.adapter_slot], np.int32),
            "ascales": scales,
        }

    def _window_size(self, pending: int = 0) -> int:
        """Chunks to dispatch before the next host sync (see
        EngineConfig.max_inflight_chunks). Streaming requests pin the
        window to 1 chunk so tokens flush at chunk cadence; otherwise the
        tightest active row budget bounds the window, so no row ever has
        more than its own remaining tokens in flight. Speculation-
        eligible rows also pin the window: a multi-chunk dispatch would
        decode hundreds of tokens between draft opportunities, so while
        such a row is live the drafter gets a look every chunk (rows
        whose content never repeats stop being eligible via the
        miss-counting adaptive disable and full windows resume).

        ``pending`` is the token depth already in flight (overlap mode
        dispatches ahead of the readback): it comes off the tightest
        budget so look-ahead windows never stack past a row's remaining
        tokens."""
        e = self.engine
        K = e.engine_cfg.decode_chunk
        if any(r is not None and r.stream for r in self._rows):
            return 1
        if (
            self._spec is not None
            and self._spec_possible()
            and any(
                r is not None and self._spec_eligible(b, r)
                for b, r in enumerate(self._rows)
            )
        ):
            return 1
        min_left = min(
            r.max_new_tokens - len(r.out_ids)
            for r in self._rows
            if r is not None
        ) - pending
        w = -(-min_left // K)  # ceil
        if self._queue:  # queued work wants a row soon: keep syncs frequent
            w = min(w, 2)
        return max(1, min(w, e.engine_cfg.max_inflight_chunks))

    def _prepare_window_tables(self, extra: int):
        """Paged: grow every active row's block table to cover the next
        device call's writes (positions < offset + extra — W*K for a
        decode window, K+1 for a spec verify), then build the [bsz, tw]
        device argument at the pow2-bucketed width. A row the pool
        cannot cover even after reclaiming prefix pins fails alone
        (explicitly undersized kv_pool_blocks); returns None when no
        active rows survive."""
        for b, req in enumerate(self._rows):
            if req is None:
                continue
            try:
                self._ensure_blocks(b, int(self._offsets[b]) + extra)
            except _PoolExhausted as err:
                # migration-based failover: a row the pool can no longer
                # grow is fully recoverable state — offer it to the
                # migration hook (a peer with headroom resumes it KV-
                # intact) before the terminal typed error
                migrated = False
                if self.migrate_cb is not None and not req.cancelled:
                    try:
                        snap = self._snapshot_row(b, req)
                        # hook failures degrade to the typed error below —
                        # never into the loop's catch-all (_fail_all)
                        migrated = bool(
                            self.migrate_cb(req, snap, "pool_exhausted")
                        )
                    except Exception:  # noqa: BLE001
                        logger.exception("pool-pressure migration failed")
                    if migrated:
                        self.stats.migrated_out += 1
                self._rows[b] = None
                self._release_row(b)
                self._row_params_dirty = True
                if not migrated:
                    self._retire_error(req, str(err))
                else:
                    self._release_adapter(req)
        live = [
            len(self._row_blocks[b])
            for b, r in enumerate(self._rows) if r is not None
        ]
        if not live:
            return None
        tw = self._table_width(max(live))
        # the two proportionality counters: what the gather reads vs what
        # is actually mapped (tests + bench assert they track each other)
        self.stats.paged_live_blocks = sum(live)
        self.stats.paged_blocks_read_last_step = self._bsz * tw
        self.stats.paged_blocks_in_use = self._alloc.used_count
        return np.ascontiguousarray(self._tables[:self._bsz, :tw])

    def _spec_eligible(self, b: int, req: Request) -> bool:
        """Row-level speculation gate: greedy, not penalized, some tier
        still untried (spec_tier "off" is the ladder-exhausted terminal),
        enough budget that a draft could beat the single bonus token, and
        enough cache headroom for the fixed [B, K+1] write extent. The
        headroom clause matters for _window_size too: a spec_tokens
        larger than any row's remaining capacity (or a row approaching
        the end of the cache) must stop counting as eligible, or the
        batch would pay pinned 1-chunk windows for the rest of the
        generation with zero speculation possible — and no misses ever
        accruing to fail the tier's probe, since drafting never even
        starts."""
        e = self.engine
        return (
            req.temperature <= 0.0
            and not req.penalized
            and req.spec_tier != TIER_OFF
            and not req.cancelled
            and req.max_new_tokens - len(req.out_ids) >= 2
            and int(self._offsets[b]) + e.engine_cfg.spec_tokens + 1
            <= e.max_seq_len
        )

    def _spec_possible(self) -> bool:
        """Batch-level speculation gate, shared by _spec_drafts and the
        _window_size pin so they can never disagree: no active row within
        K+1 of capacity (ineligible rows still ride the [B, K+1] forward,
        and its write extent past capacity would demand pool blocks past
        blocks_per_row). A window pinned to 1 chunk while every spec step
        is vetoed would be pure sync-cadence loss.

        The penalized-row veto applies only to the SPLIT roots: with the
        fused root on, counts ride the verify call too
        (engine._spec_verify_fn), so one penalized row no longer parks
        the whole batch's speculation on the counts window."""
        e = self.engine
        K = e.engine_cfg.spec_tokens
        for b, req in enumerate(self._rows):
            if req is None:
                continue
            if req.penalized and not self._fused:
                return False
            if int(self._offsets[b]) + K + 1 > e.max_seq_len:
                return False
        return True

    def _spec_transition(self, req: Request, failed_tier: str):
        """Move a row whose CURRENT tier just failed (probe miss budget
        or a dead remote) to the next tier on the ladder — demotion to a
        cheaper tier when one remains untried, the n-gram -> model
        escalation otherwise, "off" when the ladder is exhausted. The
        failed tier never gets retried (requests are short-lived); the
        probe counters reset so the new tier gets a full budget."""
        req.spec_tiers_failed.add(failed_tier)
        self._spec.tiers[failed_tier].forget(req)
        req.spec_tier = self._spec.next_tier(
            failed_tier, req.spec_tiers_failed
        )
        req.spec_tier_drafted = 0
        req.spec_tier_accepted = 0
        req.spec_tier_misses = 0

    def _spec_tier_check(self, req: Request):
        """Per-tier probe verdict: drafted tokens plus miss-equivalents
        (a no-match step weighs like a fully-rejected K-token draft)
        against the acceptance floor — same should_disable math as ever,
        fed with the CURRENT tier's counters, so the probe budget is per
        tier and failure means transition, not death."""
        K = self.engine.engine_cfg.spec_tokens
        if req.spec_tier in (None, TIER_OFF):
            return
        if should_disable(
            req.spec_tier_drafted + K * req.spec_tier_misses,
            req.spec_tier_accepted,
            self.engine.engine_cfg.spec_probe_tokens,
            self.engine.engine_cfg.spec_min_accept,
        ):
            self._spec_transition(req, req.spec_tier)

    def _spec_degrade_dead(self, req: Request, tier: str, drafter):
        """Typed degradation off a dead remote tier: the row lands on the
        next LOCAL tier immediately — a dead draft peer must never stall
        or starve the decode loop."""
        reason = getattr(drafter, "dead_reason", None) or "peer_lost"
        _C_SPEC_DEGRADED.inc(1, reason=reason)
        if not getattr(drafter, "_degrade_logged", False):
            drafter._degrade_logged = True
            logger.warning(
                "mesh drafter dead (%s): degrading rows to the local tier",
                reason,
            )
        self._spec_transition(req, tier)

    def _spec_drafts(self):
        """Collect per-row drafts for one spec step, grouped by tier so
        each drafter sees its rows in ONE batched propose call (the model
        tier turns that into a single [B, 2]+scan device pass). Returns
        (drafts [bsz, K], lens [bsz]) or None when this step must take
        the plain/penalized window instead: no row drafted anything, a
        penalized row is active under the SPLIT roots (pre-fusion, the
        counts graph existed only on the window path — see
        _spec_possible), or any active row is too close to capacity for
        the fixed [B, K+1] write extent (_spec_possible).

        Tier bookkeeping per row: a None proposal is PENDING (mesh tier,
        draft still in flight — the row just skips this step, no
        accounting); [] is a miss that feeds the tier's probe; a dead
        remote tier degrades the row to the local ladder typed, right
        here, before it could cost a step."""
        e = self.engine
        K = e.engine_cfg.spec_tokens
        if not self._spec_possible():
            return None
        by_tier: dict[str, list] = {}
        for b, req in enumerate(self._rows):
            if req is None:
                continue
            # greedy non-penalized rows speculate; sampled rows ride
            # along advancing their normal one token per forward
            if not self._spec_eligible(b, req):
                continue
            if req.spec_tier is None:
                req.spec_tier = self._spec.start_tier()
            tier = req.spec_tier
            drafter = self._spec.tiers.get(tier)
            if drafter is not None and getattr(drafter, "dead", False):
                self._spec_degrade_dead(req, tier, drafter)
                tier = req.spec_tier
                drafter = self._spec.tiers.get(tier)
            if tier == TIER_OFF or drafter is None:
                continue
            by_tier.setdefault(tier, []).append((b, req))
        drafts = np.zeros((self._bsz, K), np.int32)
        lens = np.zeros((self._bsz,), np.int32)
        self._draft_tier = {}
        any_draft = False
        for tier, rows in by_tier.items():
            proposals = self._spec.tiers[tier].propose_batch(rows)
            for b, req in rows:
                d = proposals.get(b)
                if d is None:
                    continue  # pending (mesh pipeline): not a miss
                if not d:
                    req.spec_misses += 1
                    req.spec_tier_misses += 1
                    self._spec_tier_check(req)
                    continue
                left = req.max_new_tokens - len(req.out_ids)
                # past-budget draft positions are dead weight; a remote
                # drafter gets clipped to K defensively too
                d = list(d)[:K][:left - 1]
                if not d:
                    continue
                drafts[b, :len(d)] = d
                lens[b] = len(d)
                self._draft_tier[b] = tier
                any_draft = True
        return (drafts, lens) if any_draft else None

    def _spec_step(self) -> bool:
        """One speculative step: verify every drafting row's proposal in
        a single [B, K+1] forward; offsets advance by accepted+1 per row
        (rejected positions sit at/past the new offset, where the causal
        invariant hides them — see engine._spec_verify_fn). Returns False
        when the step was not taken and the caller should run a normal
        decode window."""
        proposal = self._spec_drafts()
        if proposal is None:
            return False
        drafts, lens = proposal
        e = self.engine
        # cover the whole [offset, offset+K+1) write extent — blocks
        # claimed for later-rejected slots stay owned by the row
        # (over-allocated tail) and free normally at retirement
        tables = self._prepare_window_tables(e.engine_cfg.spec_tokens + 1)
        if tables is None:
            self._compact_and_shrink()
            return True  # nothing left to decode this step
        temps, topks, topps = self._row_sampling_arrays()
        minps = self._minps if self._minps.any() else None
        self._set_fill_gauges()
        # economics: the hardware runs bsz*(K+1) positions; the batch
        # SCHEDULED active*(K+1) token slots, of which only accepted
        # drafts + the bonus token will prove useful (_process_row_tokens).
        # Mean depth DURING the step includes the in-flight half-window,
        # same convention as the decode-window dispatch below
        self._meter.record_dispatch(
            self._bsz * (e.engine_cfg.spec_tokens + 1),
            self._mean_active_ctx() + (e.engine_cfg.spec_tokens + 1) / 2.0,
            scheduled=self.active * (e.engine_cfg.spec_tokens + 1),
        )
        # fused penalty bookkeeping: with the fused root on, a penalized
        # row no longer vetoes the whole batch's speculation — its counts
        # ride the verify call (engine._spec_verify_fn) and it advances
        # its normal one penalty-sampled token per step
        pen = (
            self._fused and self._counts is not None
            and any(r is not None and r.penalized for r in self._rows)
        )
        t_step = time.perf_counter()
        with get_tracer().span(
            "engine.spec_verify", active=self.active, drafted=int(lens.sum())
        ):
            if pen:
                nxt_d, self._cache, acc_d, self._counts = e._spec_verify(
                    e.params, self._cur, drafts, lens, self._cache,
                    self._offsets, temps, topks, topps, minps,
                    e._next_key(), tables, **self._lora_args(),
                    counts=self._counts, reps=self._reps,
                    press=self._press, freqs=self._freqs,
                )
                self.stats.counts_windows += 1
            else:
                nxt_d, self._cache, acc_d = e._spec_verify(
                    e.params, self._cur, drafts, lens, self._cache,
                    self._offsets, temps, topks, topps, minps,
                    e._next_key(), tables, **self._lora_args(),
                )
            # a spec step is always a serialized sync: the drafter needs
            # the verdict before it can propose again
            _C_HOST_SYNCS.inc()
            _C_SYNC_STALLS.inc()
            _G_OVERLAP.set(0)
            nxt, acc = (np.asarray(x) for x in jax.device_get((nxt_d, acc_d)))  # meshlint: ignore[ML-J003] -- the spec verdict IS the readback window's one host sync
        _H_STEP.observe((time.perf_counter() - t_step) * 1000.0)
        self._last_dispatch_t = time.perf_counter()
        self._cur = nxt.astype(np.int32).copy()
        self._offsets = (self._offsets + acc + 1).astype(np.int32)
        self.stats.spec_steps += 1

        retired_any = False
        for b, req in enumerate(self._rows):
            if req is None:
                continue
            req.chunks_decoded += 1
            a = int(acc[b])
            drafted_here = int(lens[b])
            tier = self._draft_tier.get(b, "ngram")
            if drafted_here:
                req.spec_drafted += drafted_here
                req.spec_accepted += a
                req.spec_tier_drafted += drafted_here
                req.spec_tier_accepted += a
                self.stats.spec_drafted += drafted_here
                self.stats.spec_accepted += a
                ts = self.stats.spec_tiers.setdefault(
                    tier, {"drafted": 0, "accepted": 0}
                )
                ts["drafted"] += drafted_here
                ts["accepted"] += a
                _C_SPEC_DRAFTED.inc(drafted_here, tier=tier)
                _C_SPEC_ACCEPTED.inc(a, tier=tier)
                self._meter.note_spec(tier, drafted_here, a)
            # accepted draft prefix, then the verify's own next token
            retired = self._process_row_tokens(
                b, req, list(drafts[b, :a]) + [nxt[b]]
            )
            retired_any |= retired
            if drafted_here and not retired:
                # the verdict rolls the drafter's state forward (model:
                # KV frontier; mesh: pipeline the next draft_request NOW
                # so its RTT overlaps the target's next step) — AFTER
                # _process_row_tokens so the drafter sees the grown
                # context. Then the probe check, which may transition.
                drafter = self._spec.tiers.get(tier)
                if drafter is not None:
                    drafter.observe(req, a)
                self._spec_tier_check(req)
        if retired_any:
            self._compact_and_shrink()
        return True

    def _set_fill_gauges(self):
        """Batch utilization snapshot before a device step: how full the
        bucket is and the absolute active-row count."""
        a = self.active
        _G_ACTIVE_ROWS.set(a)
        _G_BATCH_FILL.set(a / self._bsz if self._bsz else 0.0)
        # pool-growth forecast (engine/introspect.py): sampled on the
        # dispatch cadence so the pool_exhaust_eta gauge the admission
        # shed reads tracks the live allocation trend
        self.engine.introspect.forecast.feed(
            self._alloc.used_count, self._alloc.free_count
        )

    def _mean_active_ctx(self) -> float:
        """Mean cache depth of the active rows — the attention-term input
        of the FLOPs model (introspect.GoodputMeter)."""
        depths = [
            int(self._offsets[b])
            for b, r in enumerate(self._rows) if r is not None
        ]
        return sum(depths) / len(depths) if depths else 0.0

    def _process_row_tokens(self, b: int, req: Request, tokens) -> bool:
        """THE per-row token-intake protocol, shared by the decode-window
        and spec-step paths (a retirement/streaming semantics change must
        hit both identically): mark cancellation, accept tokens until the
        request finishes, emit the stream event, retire a done row.
        Returns True when the row retired."""
        if req.cancelled and not req.done:
            req.finish = "cancelled"
        emitted: list[int] = []
        for t in tokens:
            if not req.accept(int(t)):
                break
            emitted.append(int(t))
            if req.done:  # budget exhausted exactly on this token
                break
        # goodput accounting: only tokens ACCEPTED into an output are
        # useful — post-EOS overshoot, rejected draft positions and
        # cancelled-row tokens all stay scheduled-only
        self._meter.note_useful(len(emitted))
        if emitted and req.stream:
            req.events.put({
                "token": emitted[-1],
                "tokens": emitted,
                "text": req.text_delta(final=req.done),
            })
        if req.done:
            self._rows[b] = None
            self._release_row(b)
            self._row_params_dirty = True
            self._retire(req)
            return True
        return False

    def _step(self):
        """One hot-loop turn (docs/PERF.md "Decode hot loop"): keep the
        readback ring full, fetch the OLDEST in-flight window (the only
        host sync), refill the ring BEFORE processing its tokens — so
        token emission/stop handling/accounting overlap the next window's
        device time — then process. With overlap off the ring depth is 1
        and this collapses to the classic dispatch→sync→process loop.
        With speculation enabled, a turn where some greedy row drafted
        becomes ONE serialized [B, K+1] verify call instead (_spec_step
        — the drafter needs each verdict before proposing again, so spec
        steps never ride the ring)."""
        K = self.engine.engine_cfg.decode_chunk
        if (not self._inflight and self._spec is not None
                and self._spec_step()):
            return
        # fill the ring: the first window dispatches unconditionally (the
        # classic step); look-ahead windows pass the _overlap_ready gate
        depth = self._depth if self._overlap else 1
        while len(self._inflight) < depth:
            pending = sum(r["W"] for r in self._inflight) * K
            if self._inflight and not self._overlap_ready(pending):
                break
            if not self._dispatch_window(pending):
                break
        if not self._inflight:
            self._compact_and_shrink()
            return
        rec = self._inflight.popleft()
        toks_host = self._fetch_window(rec)
        # async dispatch overlap: with rec's tokens on the host, put the
        # NEXT window in flight before doing any host-side token work
        # (rec's tokens count toward pending — they are not in out_ids
        # yet). At depth 1 this alone keeps the device busy through the
        # processing below; at depth 2 the ring already holds a window
        # and this tops it back up.
        if self._overlap:
            while len(self._inflight) < self._depth:
                pending = (sum(r["W"] for r in self._inflight)
                           + rec["W"]) * K
                if not self._overlap_ready(pending):
                    break
                if not self._dispatch_window(pending):
                    break
        if not self._inflight:
            # the device goes idle while the host processes this window —
            # the stall the overlap machinery exists to remove
            _C_SYNC_STALLS.inc()
        retired_any = self._process_window(rec, toks_host)
        self._release_deferred()
        if self.active == 0 and self._inflight:
            # every row retired mid-ring: the remaining windows are pure
            # overshoot nobody will read — drain them now so the batch
            # can compact and the next admission starts clean
            retired_any |= self._drain_inflight()
        if retired_any and not self._inflight:
            # compaction moves rows; in-flight records carry row indices,
            # so it must wait for an empty ring (holes cost dead-row
            # positions until then — the same price a half-empty bucket
            # already pays)
            self._compact_and_shrink()

    def _dispatch_window(self, pending: int = 0) -> bool:
        """Dispatch one W-chunk decode window (async — no host sync) and
        push its record onto the readback ring. Chains device state off
        the ring tail (or the host mirrors when the ring is empty), so
        windows form one dependency chain on device. Host offsets advance
        AT DISPATCH — every pending-window consumer (_prepare_window_
        tables, _spec_eligible, _overlap_ready) sees the post-in-flight
        positions. Returns False when no active rows survive table prep."""
        e = self.engine
        K = e.engine_cfg.decode_chunk
        W = self._window_size(pending)
        tables = self._prepare_window_tables(W * K)
        if tables is None:
            return False
        temps, topks, topps = self._row_sampling_arrays()
        pen = self._counts is not None and any(
            r is not None and r.penalized for r in self._rows
        )
        # None selects the min_p-free trace: the relative-floor softmax
        # must cost nothing when no active row asked for it. Gate on the
        # SAME array the sampler receives — a row scan could silently
        # diverge from how _row_sampling_arrays builds _minps
        minps = self._minps if self._minps.any() else None
        self._set_fill_gauges()
        # economics: bsz*W*K positions run (dead rows included — the
        # hardware computes them); active*W*K token slots are scheduled
        self._meter.record_dispatch(
            self._bsz * W * K,
            self._mean_active_ctx() + W * K / 2.0,
            scheduled=self.active * W * K,
        )
        # host mirrors go in as the first call's args; chunks chain on
        # the returned DEVICE arrays; the host mirrors then advance
        # from the same readback the tokens needed anyway — the whole
        # window runs with zero eager device ops
        if self._inflight:
            tail = self._inflight[-1]
            cur_d, off_d = tail["cur"], tail["off"]
        else:
            cur_d, off_d = self._cur, self._offsets
            if self._chain_sharding is not None:
                # jax keys executables on input sharding as well as
                # shape: a raw numpy mirror lowers as an UNcommitted
                # arg while chained jit outputs carry the mesh's
                # NamedSharding, which would silently DOUBLE the decode
                # root's compile space (one executable per key per
                # source) and land the second compile mid-serve.
                # Committing the mirrors to the sharding the root's own
                # outputs carry keeps one executable per sentinel key.
                cur_d = jax.device_put(cur_d, self._chain_sharding[0])
                off_d = jax.device_put(off_d, self._chain_sharding[1])
        lora = self._lora_args()
        toks_parts = []
        for _ in range(W):
            if self._fused:
                cur_d, self._cache, off_d, cnts, toks = self._decode(
                    e.params, cur_d, self._cache, off_d,
                    temps, topks, topps, minps, e._next_key(), tables,
                    counts=self._counts if pen else None,
                    reps=self._reps if pen else None,
                    press=self._press if pen else None,
                    freqs=self._freqs if pen else None,
                    **lora,
                )
                if pen:
                    self._counts = cnts
            elif pen:
                cur_d, self._cache, off_d, self._counts, toks = (
                    self._decode_pen(
                        e.params, cur_d, self._cache, off_d, self._counts,
                        temps, topks, topps, minps,
                        self._reps, self._press, self._freqs,
                        e._next_key(), tables, **lora,
                    )
                )
            else:
                # _decode is the fused root in BOTH modes; with counts
                # left None it lowers to the counts-free graph, so the
                # unfused setting differs only in routing pen windows to
                # the split _decode_pen root above
                cur_d, self._cache, off_d, _, toks = self._decode(
                    e.params, cur_d, self._cache, off_d,
                    temps, topks, topps, minps, e._next_key(), tables,
                    **lora,
                )
            toks_parts.append(toks)
        if self._chain_sharding is None:
            # metadata-only read (no sync): adopt the root's own output
            # shardings as the canonical chain-entry commitment
            self._chain_sharding = (cur_d.sharding, off_d.sharding)
        self._inflight.append({
            "cur": cur_d, "off": off_d, "toks": toks_parts, "W": W,
            # each record carries its own (row, request) map: retirement
            # nulls _rows[b] between dispatch and fetch, and the fetch
            # must still route row b's tokens to the request that was
            # live when the window launched
            "rows": [
                (b, r) for b, r in enumerate(self._rows) if r is not None
            ],
            "t0": time.perf_counter(),
        })
        self._offsets = self._offsets + np.int32(W * K)
        self.stats.chunks += W
        if pen:
            self.stats.counts_windows += 1
        self._last_dispatch_t = time.perf_counter()
        return True

    def _overlap_ready(self, pending: int) -> bool:
        """May a look-ahead window dispatch with ``pending`` tokens
        already in flight? Look-ahead is strictly opportunistic — it must
        never be DESTRUCTIVE (evict prefix pins, migrate or retire rows)
        and never steal the sync cadence from work that wants the host
        (queued admissions, checkpoints, streaming flushes, spec drafts).
        Everything here reads post-in-flight offsets (_dispatch_window
        advances them at dispatch)."""
        if not self._overlap or self.active == 0:
            return False
        # queued/checkpoint work needs settled rows at the next sync;
        # streaming rows need token flushes at chunk cadence, not
        # pending*K tokens late
        if self._queue or self._checkpoints:
            return False
        if any(r is not None and r.stream for r in self._rows):
            return False
        # a spec-eligible row wants a draft look at the NEXT readback —
        # stacking plain windows ahead of it would decode past the
        # repetition the drafter feeds on
        if (
            self._spec is not None
            and self._spec_possible()
            and any(
                r is not None and self._spec_eligible(b, r)
                for b, r in enumerate(self._rows)
            )
        ):
            return False
        e = self.engine
        K = e.engine_cfg.decode_chunk
        min_left = min(
            r.max_new_tokens - len(r.out_ids)
            for r in self._rows
            if r is not None
        )
        # some row must still need tokens BEYOND what is already in
        # flight, or the whole window would be budget overshoot
        if min_left <= pending:
            return False
        W = self._window_size(pending)
        need = 0
        for b, r in enumerate(self._rows):
            if r is None:
                continue
            upto = int(self._offsets[b]) + W * K
            # hard capacity: the non-overlap path may overshoot into the
            # decode_chunk margin once; stacked look-ahead may not
            if upto > e.max_seq_len:
                return False
            need += max(
                0, ceil_div(upto, self._block_size) - len(self._row_blocks[b])
            )
        # the free list must cover the window outright: look-ahead never
        # reclaims prefix pins and never migrates/retires a row
        return need <= self._alloc.free_count

    def _fetch_window(self, rec) -> np.ndarray:
        """THE host sync of the decode hot loop: block on one in-flight
        window's token buffers. Everything else the step needs came back
        with earlier fetches or never left the host."""
        _G_OVERLAP.set(len(self._inflight))
        _C_HOST_SYNCS.inc()
        with get_tracer().span(
            "engine.decode_window",
            active=len(rec["rows"]), chunks=rec["W"],
            inflight=len(self._inflight),
        ):
            parts = [np.asarray(x) for x in jax.device_get(rec["toks"])]  # meshlint: ignore[ML-J003] -- the one sanctioned sync per readback window (docs/PERF.md)
        toks_host = (
            np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
        )  # [B, W*K]
        if not self._inflight:
            # ring drained: the host mirror of the latest sampled token
            # is this window's last column (mid-ring fetches skip this —
            # a NEWER window is already chained off the device value)
            self._cur = toks_host[:, -1].astype(np.int32).copy()
        _H_STEP.observe((time.perf_counter() - rec["t0"]) * 1000.0)
        return toks_host

    def _process_window(self, rec, toks_host: np.ndarray) -> bool:
        """Route one fetched window's tokens through the shared per-row
        intake (_process_row_tokens). Rows that retired or moved since
        dispatch are skipped — their overshoot tokens are scheduled-only
        work the goodput meter already books as waste."""
        retired_any = False
        for b, req in rec["rows"]:
            if self._rows[b] is not req or req.done:
                continue
            req.chunks_decoded += rec["W"]
            retired_any |= self._process_row_tokens(b, req, toks_host[b])
        return retired_any

    def _drain_inflight(self) -> bool:
        """Fetch + process every in-flight window (admission, checkpoints
        and shutdown paths need settled row state). Each drained fetch is
        a stall by definition — the device goes idle behind it."""
        retired_any = False
        while self._inflight:
            rec = self._inflight.popleft()
            _C_SYNC_STALLS.inc()
            toks_host = self._fetch_window(rec)
            retired_any |= self._process_window(rec, toks_host)
        self._release_deferred()
        return retired_any

    def _release_deferred(self):
        """Free blocks whose rows retired while windows were in flight —
        only once the ring is empty (until then, in-flight windows still
        dead-row-scatter into them)."""
        if self._deferred_blocks and not self._inflight:
            self._alloc.deref(self._deferred_blocks)
            self._deferred_blocks = []
            self.stats.paged_blocks_in_use = self._alloc.used_count

    def _retire(self, req: Request):
        self._release_adapter(req)
        if self._spec is not None:
            self._spec.forget(req)  # drafter KV slot / mesh server row
        req.timing.t_done = time.perf_counter()
        self.stats.retired += 1
        self.stats.history.append(
            {"new_tokens": len(req.out_ids), "chunks": req.chunks_decoded}
        )
        req.events.put({"done": True, "result": self.engine._build_result(req)})

    def _retire_error(self, req: Request, reason: str):
        """Error-terminate an ADMITTED row with full retirement accounting
        (retired/history/t_done) — `admitted - retired` must not drift for
        rows the pool failed mid-decode."""
        self._release_adapter(req)
        if self._spec is not None:
            self._spec.forget(req)
        req.finish = "error"
        req.timing.t_done = time.perf_counter()
        self.stats.retired += 1
        self.stats.history.append(
            {"new_tokens": len(req.out_ids), "chunks": req.chunks_decoded,
             "error": True}
        )
        req.events.put({"done": True, "result": None, "error": reason})
