"""Continuous-batching scheduler: shared-cache decode with rolling admission.

The round-1 engine dispatched every decode chunk of a request up front and
truncated host-side afterwards — a request stopping at 10 tokens with
max_new_tokens=2048 still paid ~2048 decode steps, and concurrent requests
were independent batch-1 programs contending for the chip. This scheduler
replaces both (the reference's torch path stops at EOS per request but has
no batching at all — reference hf.py:84-108):

- **One shared KV cache** ``[L, bsz, S, Hkv, hd]`` plus per-row device
  state (current token, write offset). All rows decode together in one
  compiled program per chunk; on TPU, decode is HBM-bandwidth-bound on the
  weights, so batched rows ride along nearly free — this is the route to
  the BASELINE throughput ladder, not bigger single streams.
- **Adaptive batch bucketing**: ``bsz`` tracks the active row count in
  power-of-two buckets (grow on admission, shrink on retirement, capped at
  max_batch). Idle rows are not free — each dead row still streams its
  full cache slice through HBM every step (measured 4x decode cost at
  bsz=8 with one active row on a v5e chip) — so a solo request decodes at
  bsz=1 speed. Active rows are kept compacted in [0, active) by moving the
  highest row into retirement holes (one row-copy per retirement). Each
  bucket size compiles the decode program once.
- **Rolling admission**: new requests prefill into a private row cache
  (bucketed, compile-bounded) and are spliced into a free batch row via one
  donated dynamic_update_slice program. Admission happens between decode
  chunks; nothing waits for the batch to drain.
- **EOS early-exit**: tokens are read back every chunk; a row whose request
  hit a stop token or its token budget retires immediately and frees the
  row for the next queued request. Per-request decode cost is
  ceil(tokens_actually_generated / decode_chunk) chunks.
- **Per-row sampling** (sampling.sample_batched): temperature/top-k/top-p
  ride as [B] arrays inside the one compiled step, so mixed sampling
  settings never force a recompile.

Threading model: one daemon scheduler thread owns all device state; public
submit() only appends to a queue under a condition variable. Stream
consumers read per-request event queues (queue.Queue), so gateway threads
never touch jax state — the single-owner rule that keeps this race-free.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..tracing import get_tracer

logger = logging.getLogger("bee2bee_tpu.scheduler")


@dataclass
class _Timing:
    t_submit: float = 0.0
    t_first: float = 0.0  # first token available (ttft reference point)
    t_done: float = 0.0


class Request:
    """One in-flight generation. Consumers read .events until a done event;
    the scheduler thread is the only producer."""

    def __init__(
        self,
        ids: list[int],
        max_new_tokens: int,
        temperature: float,
        top_k: int,
        top_p: float,
        stop: set[int],
        eos: int | None,
        tokenizer,
        stream: bool = False,
        repetition_penalty: float = 1.0,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        min_p: float = 0.0,
    ):
        self.stream = stream
        # set by an abandoning consumer (generate_stream closed early);
        # plain bool write cross-thread — the scheduler thread reads it at
        # chunk boundaries and retires the row
        self.cancelled = False
        self.ids = ids
        self.max_new_tokens = max_new_tokens
        self.temperature = float(temperature if temperature is not None else 0.0)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p if top_p is not None else 1.0)
        self.min_p = float(min_p or 0.0)
        self.stop = stop
        self.eos = eos
        self.repetition_penalty = float(repetition_penalty or 1.0)
        self.presence_penalty = float(presence_penalty or 0.0)
        self.frequency_penalty = float(frequency_penalty or 0.0)
        self.tokenizer = tokenizer
        self.events: queue.Queue = queue.Queue()
        self.out_ids: list[int] = []
        self.finish: str | None = None
        self.timing = _Timing(t_submit=time.perf_counter())
        self.prompt_tokens = len(ids)
        self.bucket = 0
        self.chunks_decoded = 0  # observability: early-exit is visible here
        self._flushed_text = ""

    # ---- token accounting (runs on the scheduler thread) ----

    def accept(self, tok: int) -> bool:
        """Feed one sampled token; returns False when the request is done
        (budget reached / stop token) — the token is NOT kept then."""
        if self.finish is not None:
            return False
        if len(self.out_ids) >= self.max_new_tokens:
            self.finish = "length"
            return False
        if tok in self.stop:
            self.finish = "eos" if tok == self.eos else "stop"
            return False
        self.out_ids.append(tok)
        if len(self.out_ids) >= self.max_new_tokens:
            self.finish = "length"  # budget exhausted by this token
        return True

    def text_delta(self, final: bool = False) -> str:
        """Cumulative-decode → UTF-8-safe incremental text (holds back a
        trailing replacement char until the multi-byte token completes)."""
        full = self.tokenizer.decode(self.out_ids)
        if not final:
            full = full.rstrip("�")
        delta = full[len(self._flushed_text):]
        self._flushed_text = full
        return delta

    @property
    def done(self) -> bool:
        return self.finish is not None

    @property
    def penalized(self) -> bool:
        """True when any occurrence penalty is active — such rows route
        through the scheduler's counts-carrying decode variant."""
        return (
            self.repetition_penalty != 1.0
            or self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
        )


@dataclass
class SchedulerStats:
    admitted: int = 0
    retired: int = 0
    chunks: int = 0  # batched decode chunks dispatched
    peak_active: int = 0
    prefix_hits: int = 0
    prefix_tokens_saved: int = 0
    history: deque = field(default_factory=lambda: deque(maxlen=64))


class PrefixCache:
    """LRU of prompt K/V snapshots: key = token-id tuple, value = a batch-1
    row cache valid for positions [0, len(key)).

    Lookup returns the entry sharing the longest common prefix with the
    incoming prompt, capped at len(prompt) - 1 — the final prompt token
    always prefills so admission gets its last_logits for the first
    sample. A key LONGER than the prompt is usable too (identical-prompt
    repeats, a truncated retry): its positions beyond the match are stale
    but the engine's causal invariant already guarantees any position >=
    the write offset is either masked or overwritten at write time.
    Entries are device pytrees; the scheduler thread owns all access, so
    no locking. Capacity is small (entries are row-cache-sized in HBM);
    the linear prefix scan over <= capacity keys is noise."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._entries: dict[tuple, object] = {}  # insertion-ordered (LRU)

    def match(self, ids: list[int]):
        """-> (m, row_cache | None): longest usable cached prefix."""
        cap = len(ids) - 1
        best_key, best_m = None, 0
        for key in self._entries:
            m = min(len(key), cap)
            if m > best_m and tuple(ids[:m]) == key[:m]:
                best_key, best_m = key, m
        if best_key is None:
            return 0, None
        entry = self._entries.pop(best_key)  # LRU touch
        self._entries[best_key] = entry
        return best_m, entry

    def has(self, ids: list[int]) -> bool:
        return tuple(ids) in self._entries

    def put(self, ids: list[int], row_cache) -> None:
        key = tuple(ids)
        self._entries.pop(key, None)
        self._entries[key] = row_cache
        while len(self._entries) > self.capacity:
            self._entries.pop(next(iter(self._entries)))


class BatchScheduler:
    """Owns the shared cache + row table; see module docstring."""

    def __init__(self, engine, max_batch: int):
        self.engine = engine
        self.max_batch = max_batch
        self.stats = SchedulerStats()
        self._queue: deque[Request] = deque()
        self._cond = threading.Condition()
        self._shutdown = False

        e = engine
        self._bsz = 1  # current batch bucket (pow2-ish, <= max_batch)
        self._cache = e.new_cache(self._bsz)
        # cur/offsets live as HOST numpy mirrors: every eager device op is
        # a blocking round trip on a tunneled chip (~1 s each, measured),
        # so the scheduler never runs eager jnp — host state goes in as
        # jit arguments (a cheap [B] transfer) and comes back with the
        # token readback it needed anyway
        self._cur = np.zeros((self._bsz,), np.int32)
        self._offsets = np.zeros((self._bsz,), np.int32)
        self._rows: list[Request | None] = [None] * self._bsz
        self._row_params_dirty = True
        self._temps = self._topps = self._topks = self._minps = None
        self._reps = self._press = self._freqs = None
        # occurrence counts [bsz, V] int32 for penalty sampling — allocated
        # lazily on the first penalized admission so the common (bench)
        # path never allocates or threads it. Rows of non-penalized
        # requests may hold stale counts; they are never read (rep=1/
        # pres=0/freq=0 rows pass through apply_penalties unchanged) and
        # every admission overwrites its row with a fresh prompt bincount.
        self._counts = None
        self._vocab = e.model_cfg.vocab_size

        # splice a batch-1 prefill cache into batch row b (donate the big
        # cache so XLA updates it in place in HBM)
        def insert(cache, row_cache, b):
            def ins(big, row):
                idx = (0, b) + (0,) * (big.ndim - 2)
                return jax.lax.dynamic_update_slice(big, row.astype(big.dtype), idx)

            return jax.tree.map(ins, cache, row_cache)

        # copy batch row src -> dst (compaction move on retirement)
        def move_row(cache, src, dst):
            def mv(big):
                row = jax.lax.dynamic_slice(
                    big, (0, src) + (0,) * (big.ndim - 2), (big.shape[0], 1) + big.shape[2:]
                )
                return jax.lax.dynamic_update_slice(
                    big, row, (0, dst) + (0,) * (big.ndim - 2)
                )

            return jax.tree.map(mv, cache)

        # old-bucket cache -> new-bucket cache (grow: splice into the fresh
        # larger cache; shrink: slice the leading rows)
        def grow(dst, src):
            return jax.tree.map(
                lambda d, s: jax.lax.dynamic_update_slice(d, s, (0,) * d.ndim),
                dst,
                src,
            )

        def shrink(src, n):
            return jax.tree.map(lambda s: s[:, :n], src)

        # counts live [B, 2, V] (batch leading, unlike the [L, B, ...]
        # cache; channel 0 = prompt occurrences, 1 = generated), so they
        # get their own row helpers
        V = self._vocab

        def c_insert(c, row, b):
            return jax.lax.dynamic_update_slice(c, row, (b, 0, 0))

        def c_move(c, src, dst):
            row = jax.lax.dynamic_slice(c, (src, 0, 0), (1, 2, V))
            return jax.lax.dynamic_update_slice(c, row, (dst, 0, 0))

        from .sampling import sample_batched

        self._insert = jax.jit(insert, donate_argnums=(0,))
        self._move_row = jax.jit(move_row, donate_argnums=(0,))
        self._grow = jax.jit(grow, donate_argnums=(0,))
        self._shrink = jax.jit(shrink, static_argnums=(1,))
        self._counts_zeros = jax.jit(
            lambda b: jnp.zeros((b, 2, V), jnp.int32), static_argnums=0
        )
        self._counts_insert = jax.jit(c_insert, donate_argnums=(0,))
        self._counts_move = jax.jit(c_move, donate_argnums=(0,))
        self._counts_bump = jax.jit(
            lambda c, b, t: c.at[b, 1, t].add(1), donate_argnums=(0,)
        )
        self._counts_shrink = jax.jit(
            lambda c, n: c[:n], static_argnums=(1,)
        )
        self._decode = jax.jit(self._decode_fn, donate_argnums=(2,))
        self._decode_pen = jax.jit(self._decode_pen_fn, donate_argnums=(2, 4))
        # jitted: sample_batched run eagerly is ~15 tiny ops = ~15 round
        # trips through a tunneled chip per admission
        self._sample_first = jax.jit(sample_batched)
        # jitted device-side deep copy (explicit jnp.copy — a bare identity
        # could alias buffers): snapshots for / restores from the prefix cache
        self._copy_cache = jax.jit(lambda c: jax.tree.map(jnp.copy, c))
        self._prefix_cache = (
            PrefixCache(e.engine_cfg.prefix_cache_entries)
            if e.engine_cfg.prefix_cache_entries > 0
            else None
        )

        self._thread = threading.Thread(
            target=self._loop, name="bee2bee-batch-scheduler", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ public

    def submit(self, req: Request) -> Request:
        with self._cond:
            if self._shutdown:
                raise RuntimeError("scheduler is shut down")
            self._queue.append(req)
            self._cond.notify()
        return req

    def shutdown(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify()
        self._thread.join(timeout=5)

    @property
    def active(self) -> int:
        return sum(r is not None for r in self._rows)

    # ------------------------------------------------------------ device fns

    def _decode_fn(self, params, cur, cache, offsets, temps, topks, topps,
                   minps, key):
        """One chunk: decode K tokens for ALL rows. Returns
        (cur', cache', offsets', toks [B, K])."""
        from ..models import core
        from .sampling import sample_batched

        e = self.engine

        def step(carry, key_t):
            cur, cache, off = carry
            logits, cache = core.forward(
                params, e.model_cfg, cur[:, None], cache, off, attn_fn=e._attn_fn()
            )
            nxt = sample_batched(
                logits[:, -1, :], key_t, temps, topks, topps, minps
            )
            return (nxt, cache, off + 1), nxt

        keys = jax.random.split(key, e.engine_cfg.decode_chunk)
        (cur, cache, offsets), toks = jax.lax.scan(step, (cur, cache, offsets), keys)
        return cur, cache, offsets, jnp.moveaxis(toks, 0, 1)

    def _decode_pen_fn(
        self, params, cur, cache, offsets, counts,
        temps, topks, topps, minps, reps, press, freqs, key,
    ):
        """Penalty-carrying decode chunk: counts ride the scan carry and
        every sampled token scatters into its row. Compiled only when a
        penalized row is active — the fast path keeps the counts-free
        graph."""
        from ..models import core
        from .sampling import sample_batched

        e = self.engine
        B = cur.shape[0]

        def step(carry, key_t):
            cur, cache, off, counts = carry
            logits, cache = core.forward(
                params, e.model_cfg, cur[:, None], cache, off, attn_fn=e._attn_fn()
            )
            nxt = sample_batched(
                logits[:, -1, :], key_t, temps, topks, topps, minps,
                counts, reps, press, freqs,
            )
            counts = counts.at[jnp.arange(B), 1, nxt].add(1)
            return (nxt, cache, off + 1, counts), nxt

        keys = jax.random.split(key, e.engine_cfg.decode_chunk)
        (cur, cache, offsets, counts), toks = jax.lax.scan(
            step, (cur, cache, offsets, counts), keys
        )
        return cur, cache, offsets, counts, jnp.moveaxis(toks, 0, 1)

    # ------------------------------------------------------------ loop

    def _loop(self):
        while True:
            with self._cond:
                while not self._queue and self.active == 0 and not self._shutdown:
                    self._cond.wait()
                if self._shutdown:
                    self._fail_all("engine shut down")
                    return
            try:
                self._admit()
                if self.active:
                    self._step()
            except Exception as e:  # noqa: BLE001 — the thread must survive:
                # a dead scheduler thread would hang every blocked caller
                logger.exception("scheduler step failed; failing active requests")
                try:
                    with self._cond:
                        self._fail_all(f"scheduler error: {e!r}")
                    self._reset_device_state()
                except Exception:
                    # recovery itself failed (dead device): stop accepting
                    # work so submit() raises instead of queueing forever
                    logger.exception("scheduler recovery failed; shutting down")
                    with self._cond:
                        self._shutdown = True
                        try:
                            self._fail_all("scheduler dead: device unrecoverable")
                        except Exception:
                            pass
                    return

    def _fail_all(self, reason: str):
        """Error-terminate every queued AND admitted request (callers are
        blocked on their event queues and must always get a done event).
        Caller must hold self._cond — submit() appends under it."""
        for req in list(self._queue) + [r for r in self._rows if r is not None]:
            req.finish = "error"
            req.events.put({"done": True, "result": None, "error": reason})
        self._queue.clear()
        self._rows = [None] * self._bsz

    def _reset_device_state(self):
        """Recover to an empty bucket-1 batch after a device-side failure
        (the old cache may hold donated/poisoned buffers)."""
        self._bsz = 1
        self._cache = self.engine.new_cache(1)
        self._cur = np.zeros((1,), np.int32)
        self._offsets = np.zeros((1,), np.int32)
        self._rows = [None]
        self._counts = None  # lazily reallocated by the next penalized admit
        self._row_params_dirty = True

    # ------------------------------------------------------- batch resizing

    def _resize(self, new_bsz: int):
        """Move to a new batch bucket. Active rows live in [0, active) —
        the copy of min(old, new) leading rows carries them all."""
        old = self._bsz
        if new_bsz == old:
            return
        if new_bsz > old:
            fresh = self.engine.new_cache(new_bsz)
            self._cache = self._grow(fresh, self._cache)
            if self._counts is not None:
                self._counts = self._grow(
                    self._counts_zeros(new_bsz), self._counts
                )
        else:
            self._cache = self._shrink(self._cache, new_bsz)
            if self._counts is not None:
                self._counts = self._counts_shrink(self._counts, new_bsz)
        cur = np.zeros((new_bsz,), np.int32)
        offs = np.zeros((new_bsz,), np.int32)
        keep = min(old, new_bsz)
        cur[:keep] = self._cur[:keep]
        offs[:keep] = self._offsets[:keep]
        self._cur = cur
        self._offsets = offs
        self._rows = self._rows[:keep] + [None] * (new_bsz - keep)
        self._bsz = new_bsz
        self._row_params_dirty = True

    def _compact_and_shrink(self):
        """Close retirement holes by moving the highest active row down,
        then drop to a smaller bucket when occupancy allows."""
        while True:
            hole = next(
                (i for i, r in enumerate(self._rows) if r is None), None
            )
            last = next(
                (i for i in range(self._bsz - 1, -1, -1) if self._rows[i] is not None),
                None,
            )
            if hole is None or last is None or last < hole:
                break
            self._cache = self._move_row(
                self._cache, np.int32(last), np.int32(hole)
            )
            if self._counts is not None:
                self._counts = self._counts_move(
                    self._counts, np.int32(last), np.int32(hole)
                )
            self._cur[hole] = self._cur[last]
            self._offsets[hole] = self._offsets[last]
            self._rows[hole] = self._rows[last]
            self._rows[last] = None
            self._row_params_dirty = True
        A = self.active
        if A == 0 and self._bsz > 1:
            # idle: fresh bucket-1 cache, nothing to carry over
            self._reset_device_state()
        elif self._bsz > 1 and A * 2 <= self._bsz // 2:
            # quarter-occupancy hysteresis: halve without thrashing at the
            # boundary (A*2 <= bsz/2  ⇔  A <= bsz/4)
            self._resize(max(1, self._bsz // 2))

    def _admit(self):
        """Prefill queued requests into free rows, growing the batch bucket
        up to max_batch. All prefills/inserts of an admission burst are
        dispatched asynchronously; the first tokens come back in ONE device
        sync (a sync costs ~75-100 ms through a tunneled chip — a burst of
        8 must not pay it 8 times while active streams sit undecoded)."""
        e = self.engine
        placed: list[tuple] = []  # (req, row, firsts_index)
        firsts: list = []
        while True:
            with self._cond:
                if not self._queue or self.active >= self.max_batch:
                    break
                req = self._queue.popleft()
            if req.cancelled:
                req.finish = "cancelled"
                req.timing.t_first = req.timing.t_done = time.perf_counter()
                req.events.put({"done": True, "result": e._build_result(req)})
                continue
            if self.active == self._bsz:
                self._resize(min(self._bsz * 2, self.max_batch))
            b = next(i for i, r in enumerate(self._rows) if r is None)

            n = len(req.ids)
            # longest cached prompt prefix: admit from there and prefill
            # only the remainder (chat transcripts grow by appending)
            start, cached = (
                self._prefix_cache.match(req.ids)
                if self._prefix_cache is not None
                else (0, None)
            )
            C = e.engine_cfg.prefill_chunk
            remaining = n - (start if cached is not None else 0)
            if C is not None and remaining > C:
                bucket = C  # chunked: one compiled shape for all lengths
            else:
                bucket = e._bucket_for(remaining)
            req.bucket = bucket
            try:
                with get_tracer().span(
                    "engine.admit", row=b, prompt_tokens=n, bucket=bucket,
                    prefix=start,
                ):
                    # np arguments throughout: jit converts them on entry
                    # (one small transfer), no eager ops, no blocking
                    if cached is not None:
                        row_cache = self._copy_cache(cached)
                        self.stats.prefix_hits += 1
                        self.stats.prefix_tokens_saved += start
                    else:
                        start = 0
                        row_cache = e.new_cache(1)
                    # walk the prompt in bucket-sized chunks writing the
                    # row cache at the running offset; a single whole-
                    # prompt bucket is the one-chunk case of the same loop
                    S = e.max_seq_len
                    pos = start
                    while True:
                        if pos + bucket > S:
                            # a write spanning past capacity would be
                            # CLAMPED by dynamic_update_slice (silently
                            # shifting K/V rows): re-anchor the final
                            # window to end at S. Tokens below the old
                            # pos are re-fed and recompute identical K/V
                            # in place — static shape preserved, no
                            # corruption. Terminates: the anchored window
                            # reaches n (n < S always).
                            pos = max(0, S - bucket)
                        chunk = req.ids[pos:pos + bucket]
                        tokens = np.zeros((1, bucket), np.int32)
                        tokens[0, :len(chunk)] = chunk
                        row_cache, last_logits = e._prefill(
                            e.params, tokens, row_cache,
                            np.asarray([len(chunk)], np.int32),
                            np.int32(pos),
                        )
                        pos += len(chunk)
                        if pos >= n:
                            break
                    if self._prefix_cache is not None and not self._prefix_cache.has(req.ids):
                        # snapshot BEFORE _insert donates row_cache away;
                        # an exact-key hit skips the redundant re-snapshot
                        # (match already LRU-touched it)
                        self._prefix_cache.put(
                            req.ids, self._copy_cache(row_cache)
                        )
                    # one arg tuple for both branches: a marshalling
                    # change must hit penalized and plain rows identically
                    sample_args = [
                        last_logits,
                        e._next_key(),
                        np.asarray([req.temperature], np.float32),
                        np.asarray([req.top_k], np.int32),
                        np.asarray([req.top_p], np.float32),
                        (np.asarray([req.min_p], np.float32)
                         if req.min_p > 0 else None),
                    ]
                    if req.penalized:
                        # prompt occurrences host-side (bincount is O(n+V)
                        # in numpy — no device round trip), shipped as the
                        # row's fresh counts; the first sample sees them.
                        # Channel 0: prompt (repetition's "seen"); channel
                        # 1: generated, fresh at zero (presence/frequency)
                        if self._counts is None:
                            self._counts = self._counts_zeros(self._bsz)
                        prompt_counts = np.bincount(
                            np.asarray(req.ids, np.int64), minlength=self._vocab
                        )[:self._vocab].astype(np.int32)
                        row_counts = np.stack(
                            [prompt_counts, np.zeros_like(prompt_counts)]
                        )[None]
                        self._counts = self._counts_insert(
                            self._counts, row_counts, np.int32(b)
                        )
                        sample_args += [
                            row_counts,
                            np.asarray([req.repetition_penalty], np.float32),
                            np.asarray([req.presence_penalty], np.float32),
                            np.asarray([req.frequency_penalty], np.float32),
                        ]
                    first = self._sample_first(*sample_args)
                    self._cache = self._insert(self._cache, row_cache, np.int32(b))
            except Exception as err:
                # the popped request is in neither _queue nor _rows: fail it
                # here or its caller hangs; then let _loop's handler recover
                # (which errors the rest of this burst — they sit in _rows)
                req.finish = "error"
                req.events.put(
                    {"done": True, "result": None, "error": f"admission failed: {err!r}"}
                )
                raise
            # reserve the row now (cur gets the real token after readback)
            self._rows[b] = req
            self._offsets[b] = n
            placed.append((req, b, len(firsts)))
            firsts.append(first)

        if not placed:
            return
        # ONE blocking gather for the whole burst (device_get on the list
        # fetches all; no eager concatenate op on device)
        toks = np.concatenate([np.asarray(x) for x in jax.device_get(firsts)])
        now = time.perf_counter()
        for req, b, i in placed:
            tok = int(toks[i])
            req.timing.t_first = now
            self.stats.admitted += 1
            if req.accept(tok) and req.stream:
                # token events (and their cumulative re-decode) are only
                # for streaming consumers; generate() reads the done event
                req.events.put(
                    {"token": tok, "tokens": [tok], "text": req.text_delta(final=req.done)}
                )
            if req.done:  # instant stop/zero-budget: free the row again
                self._rows[b] = None
                self._retire(req)
                continue
            if req.penalized and self._counts is not None:
                # the first token was sampled AFTER the prompt bincount
                # shipped; it must count toward later penalties too
                self._counts = self._counts_bump(
                    self._counts, np.int32(b), np.int32(tok)
                )
            self._cur[b] = tok
            self._row_params_dirty = True
            self.stats.peak_active = max(self.stats.peak_active, self.active)
        self._compact_and_shrink()

    def _row_sampling_arrays(self):
        if self._row_params_dirty or self._temps is None:
            temps = [r.temperature if r else 0.0 for r in self._rows]
            topks = [r.top_k if r else 0 for r in self._rows]
            topps = [r.top_p if r else 1.0 for r in self._rows]
            # host np: uploaded as jit args, never eager device arrays
            self._temps = np.asarray(temps, np.float32)
            self._topks = np.asarray(topks, np.int32)
            self._topps = np.asarray(topps, np.float32)
            self._minps = np.asarray(
                [r.min_p if r else 0.0 for r in self._rows], np.float32
            )
            self._reps = np.asarray(
                [r.repetition_penalty if r else 1.0 for r in self._rows],
                np.float32,
            )
            self._press = np.asarray(
                [r.presence_penalty if r else 0.0 for r in self._rows],
                np.float32,
            )
            self._freqs = np.asarray(
                [r.frequency_penalty if r else 0.0 for r in self._rows],
                np.float32,
            )
            self._row_params_dirty = False
        return self._temps, self._topks, self._topps

    def _window_size(self) -> int:
        """Chunks to dispatch before the next host sync (see
        EngineConfig.max_inflight_chunks). Streaming requests pin the
        window to 1 chunk so tokens flush at chunk cadence; otherwise the
        tightest active row budget bounds the window, so no row ever has
        more than its own remaining tokens in flight."""
        e = self.engine
        K = e.engine_cfg.decode_chunk
        if any(r is not None and r.stream for r in self._rows):
            return 1
        min_left = min(
            r.max_new_tokens - len(r.out_ids)
            for r in self._rows
            if r is not None
        )
        w = -(-min_left // K)  # ceil
        if self._queue:  # queued work wants a row soon: keep syncs frequent
            w = min(w, 2)
        return max(1, min(w, e.engine_cfg.max_inflight_chunks))

    def _step(self):
        """One readback window: dispatch W decode chunks (async, chained
        on device), sync once, process W*decode_chunk tokens per row."""
        e = self.engine
        temps, topks, topps = self._row_sampling_arrays()
        W = self._window_size()
        K = e.engine_cfg.decode_chunk
        pen = self._counts is not None and any(
            r is not None and r.penalized for r in self._rows
        )
        # None selects the min_p-free trace: the relative-floor softmax
        # must cost nothing when no active row asked for it. Gate on the
        # SAME array the sampler receives — a row scan could silently
        # diverge from how _row_sampling_arrays builds _minps
        minps = self._minps if self._minps.any() else None
        with get_tracer().span("engine.decode_window", active=self.active, chunks=W):
            # host mirrors go in as the first call's args; chunks chain on
            # the returned DEVICE arrays; the host mirrors then advance
            # from the same readback the tokens needed anyway — the whole
            # window runs with zero eager device ops
            cur_d, off_d = self._cur, self._offsets
            toks_parts = []
            for _ in range(W):
                if pen:
                    cur_d, self._cache, off_d, self._counts, toks = (
                        self._decode_pen(
                            e.params, cur_d, self._cache, off_d, self._counts,
                            temps, topks, topps, minps,
                            self._reps, self._press, self._freqs,
                            e._next_key(),
                        )
                    )
                else:
                    cur_d, self._cache, off_d, toks = self._decode(
                        e.params, cur_d, self._cache, off_d,
                        temps, topks, topps, minps, e._next_key(),
                    )
                toks_parts.append(toks)
            parts_host = [np.asarray(x) for x in jax.device_get(toks_parts)]
            toks_host = (
                np.concatenate(parts_host, axis=1) if W > 1 else parts_host[0]
            )  # [B, W*K]
        self._cur = toks_host[:, -1].astype(np.int32).copy()
        self._offsets = self._offsets + np.int32(W * K)
        self.stats.chunks += W

        retired_any = False
        for b, req in enumerate(self._rows):
            if req is None:
                continue
            req.chunks_decoded += W
            if req.cancelled and not req.done:
                req.finish = "cancelled"
            emitted: list[int] = []
            for t in toks_host[b]:
                if not req.accept(int(t)):
                    break
                emitted.append(int(t))
                if req.done:  # budget exhausted exactly on this token
                    break
            if emitted and req.stream:
                req.events.put({
                    "token": emitted[-1],
                    "tokens": emitted,
                    "text": req.text_delta(final=req.done),
                })
            if req.done:
                self._rows[b] = None
                self._row_params_dirty = True
                self._retire(req)
                retired_any = True
        if retired_any:
            self._compact_and_shrink()

    def _retire(self, req: Request):
        req.timing.t_done = time.perf_counter()
        self.stats.retired += 1
        self.stats.history.append(
            {"new_tokens": len(req.out_ids), "chunks": req.chunks_decoded}
        )
        req.events.put({"done": True, "result": self.engine._build_result(req)})
