"""Tokenizers: HF tokenizer when a local checkpoint provides one, byte-level
fallback otherwise (this environment has zero egress — nothing may download).

The reference requires `transformers` tokenizers unconditionally (reference
hf.py:23-32); here the fallback keeps every code path (engine, services,
mesh, bench) runnable offline, and the interface is the small subset the
engine needs.
"""

from __future__ import annotations

from pathlib import Path


class ByteTokenizer:
    """Reversible byte-level tokenizer: token = byte + 3 specials.

    ids 0..2 are pad/bos/eos; byte b maps to b+3. Works with any vocab_size
    >= 259; with tiny test vocabs (<259) bytes wrap modulo the space above
    the specials (lossy but still exercises every engine path).
    """

    pad_id = 0
    bos_id = 1
    eos_id = 2
    _OFFSET = 3

    def __init__(self, vocab_size: int = 50257):
        self.vocab_size = vocab_size
        self._span = max(vocab_size - self._OFFSET, 1)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [self._OFFSET + (b % self._span) for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids) -> str:
        data = bytes(
            (int(i) - self._OFFSET) % 256
            for i in ids
            if int(i) >= self._OFFSET
        )
        return data.decode("utf-8", errors="replace")

    @property
    def eos_token_id(self) -> int:
        return self.eos_id


class HFTokenizer:
    """Thin adapter over a transformers tokenizer loaded from a LOCAL path."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        # honor add_bos=False (continuation chunks must not get a BOS
        # injected mid-sequence) — mirrors ByteTokenizer's behavior
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids) -> str:
        return self._tok.decode([int(i) for i in ids], skip_special_tokens=True)

    @property
    def eos_token_id(self) -> int:
        return self._tok.eos_token_id if self._tok.eos_token_id is not None else -1


def load_tokenizer(model_name_or_path: str | None, vocab_size: int):
    """Local HF tokenizer if the path exists on disk, else byte fallback."""
    if model_name_or_path and Path(model_name_or_path).exists():
        try:
            return HFTokenizer(model_name_or_path)
        except Exception:
            pass
    return ByteTokenizer(vocab_size)
