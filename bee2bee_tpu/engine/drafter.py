"""The model-tier drafter: a real small model resident beside the target.

``DraftModel`` implements spec.Drafter tier "model": it holds its own
tiny weights and a RECTANGULAR KV cache ([L, B, S, Hkv, hd] —
core.init_cache; drafter contexts are short-lived and tiny, so the paged
pool machinery would be pure overhead) and drafts K tokens per eligible
row in ONE batched autoregressive pass: a [B, 2] chunk forward that
catches the cache up to the row's context tail and yields draft token 0,
then a K-1 step lax.scan of [B, 1] decode steps — one jit root, one
shape, all rows together.

KV state algebra (the whole file hangs on this): ``consumed[slot]`` is
the number of context positions with VALID cache content — every token
ctx[0..consumed) has been fed at its position. Feeds are always
CONTIGUOUS from ``consumed``, which buys a universal safety invariant:
any cache position >= a row's frontier is rewritten by the chunk that
first covers it BEFORE any query at or beyond it runs (core.forward
writes K/V before attention; causal masking hides higher positions until
then). So rejected-draft K/V, padded prime chunks, and idle-row parking
writes are all garbage-above-frontier — never observed. The per-step
bookkeeping:

- propose: feed ctx[consumed:] (1 or 2 tokens in steady state), draft K,
  set consumed = len(ctx). The scan also wrote K/V for drafts[0..K-2].
- observe(accepted=a): the target kept drafts[:a] + a bonus token, so
  consumed += min(a, K-1) — accepted drafts' K/V is already valid; the
  bonus (and a full-accept's draft K-1) gets fed next propose. The gap
  len(ctx) - consumed stays in {1, 2} while the row drafts every step.
- a row that skipped drafting for some steps (eligibility flapped) or a
  fresh/re-primed row catches up through batched [B, W] prime chunks.
- rejection-heavy rows (consecutive zero-accept streak) re-prime from
  scratch — the typed escape hatch for any host/device state drift.

Idle rows in a batched call park at ``_idle_off`` — a fixed offset past
every reachable real frontier — so one fixed-shape root serves any
active subset without touching inactive rows' live state.

Loaded beside the target in engine/engine.py (BEE2BEE_DRAFTER /
--drafter), which runs the tokenizer compatibility gate below first: a
drafter whose token ids mean different strings than the target's would
be a silent garbage-draft loop (acceptance ~0, all verify FLOPs wasted),
so vocab-size or tokenizer-fingerprint mismatch is a typed
``DrafterLoadError`` at boot.
"""

from __future__ import annotations

import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models import config as model_config
from ..models import core
from .spec import Drafter


class DrafterLoadError(RuntimeError):
    """Drafter/target incompatibility detected at boot (never at serve
    time): vocab-size mismatch, tokenizer-fingerprint mismatch, or a
    drafter spec that cannot resolve to a model."""


def tokenizer_fingerprint(tok) -> str:
    """Stable identity hash for a tokenizer: two tokenizers with the same
    fingerprint map ids to the same strings. HF tokenizers hash their
    full vocab table; the byte fallback is fully determined by its type
    and vocab size."""
    inner = getattr(tok, "_tok", None)
    if inner is not None and hasattr(inner, "get_vocab"):
        blob = json.dumps(sorted(inner.get_vocab().items()), ensure_ascii=True)
        return "vocab:" + hashlib.sha256(blob.encode()).hexdigest()
    return f"{type(tok).__name__}:{getattr(tok, 'vocab_size', 0)}"


def validate_drafter_compat(target_cfg, target_tok, draft_cfg, draft_tok):
    """The boot-time gate: draft token ids must BE target token ids."""
    if draft_cfg.vocab_size != target_cfg.vocab_size:
        raise DrafterLoadError(
            f"drafter vocab_size {draft_cfg.vocab_size} != target "
            f"vocab_size {target_cfg.vocab_size}: draft ids would be "
            f"garbage to the verify path"
        )
    tf, df = tokenizer_fingerprint(target_tok), tokenizer_fingerprint(draft_tok)
    if tf != df:
        raise DrafterLoadError(
            f"drafter tokenizer {df} != target tokenizer {tf}: same vocab "
            f"size but different id->string maps"
        )


class _Slot:
    __slots__ = ("idx", "consumed", "zero_streak")

    def __init__(self, idx: int):
        self.idx = idx
        self.consumed = 0
        self.zero_streak = 0


class DraftModel(Drafter):
    """Tier "model": batched K-token drafting with a resident small model.

    One instance per engine, sized to the engine's max_batch; per-request
    cache rows are slot-assigned on first propose and released by
    forget() at retirement. All jax work happens on the scheduler thread
    (same discipline as the verify root)."""

    tier = "model"

    # consecutive all-rejected verify verdicts before a full re-prime —
    # the drift escape hatch; cheap because re-priming is W tokens/step
    REPRIME_AFTER = 4
    PRIME_WIDTH = 64

    def __init__(
        self,
        model,
        spec_tokens: int,
        batch: int,
        target_max_seq_len: int,
        dtype="float32",
        seed: int = 0,
        checkpoint_path: str | None = None,
        params=None,
        sentinel=None,
    ):
        if spec_tokens < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
        try:
            self.cfg = model_config.resolve_model_config(model, checkpoint_path)
        except KeyError as e:
            raise DrafterLoadError(f"unknown drafter model {model!r}") from e
        self.spec_tokens = K = spec_tokens
        self.batch = batch
        self.dtype = jnp.dtype(dtype)
        # the longest context we draft at: the drafter's own positional
        # capacity caps it (gpt2-class drafters have learned positions);
        # rows beyond this miss instead of indexing garbage embeddings
        self.cap = min(target_max_seq_len, self.cfg.max_seq_len - K - 1)
        self.prime_width = W = min(self.PRIME_WIDTH, max(self.cap, 8))
        # idle rows park past every reachable real frontier (a real row's
        # writes reach at most cap + K - 2), so a batched call never
        # clobbers an inactive row's valid prefix
        self._idle_off = self.cap + K - 1
        S = self._idle_off + max(W, K) + 1
        self.seq_len = S

        if params is None:
            params = core.init_params(
                self.cfg, jax.random.key(seed), dtype=self.dtype
            )
        if (
            jax.default_backend() == "cpu"
            and not isinstance(params.get("layers"), (list, tuple))
        ):
            # same CPU GEMM-packing fast path the target engine uses
            params = core.unstack_layers(jax.device_get(params))
        self.params = params
        self.cache = core.init_cache(self.cfg, batch, S, dtype=self.dtype)
        self.tokenizer = None
        if checkpoint_path:
            from .tokenizer import load_tokenizer

            self.tokenizer = load_tokenizer(
                checkpoint_path, self.cfg.vocab_size
            )

        self._slots: dict[int, _Slot] = {}      # id(req) -> slot state
        self._free = list(range(batch))

        draft = jax.jit(self._draft_fn, donate_argnums=(1,))
        prime = jax.jit(self._prime_fn, donate_argnums=(1,))
        if sentinel is not None:
            # one declared shape each ([B,2] / [B,W]): any other trace
            # through these roots is a genuine storm
            draft = sentinel.watch(
                "draft", draft,
                key_fn=lambda p, c, t, *a: tuple(t.shape),
                allowed=lambda key: key == (batch, 2),
            )
            prime = sentinel.watch(
                "draft_prime", prime,
                key_fn=lambda p, c, t, *a: tuple(t.shape),
                allowed=lambda key: key == (batch, W),
            )
        self._draft = draft
        self._prime = prime

    # --------------------------------------------------------- jit roots
    def _prime_fn(self, params, cache, tokens, offsets):
        """Catch-up chunk: write K/V for tokens at [offset, offset+W) per
        row; logits discarded. Padded tails and idle rows write garbage
        above their frontiers — safe by the contiguity invariant."""
        _, cache = core.forward(params, self.cfg, tokens, cache, offsets)
        return cache

    def _draft_fn(self, params, cache, tokens, tlen, offsets):
        """The draft root: one [B, 2] chunk + a K-1 step scan of [B, 1]
        decode steps = K greedy draft tokens per row.

        tokens[b] = ctx[consumed:] right-padded to 2; tlen[b] in {1, 2};
        offsets[b] = consumed (where tokens[b, 0] is written). Draft 0 is
        the argmax at chunk index tlen-1 (the context's last token);
        drafts 1..K-1 come from feeding each draft back at position
        offset + tlen + j. The pad slot of a tlen=1 row is overwritten by
        draft 0's own feed one step later."""
        B = tokens.shape[0]
        K = self.spec_tokens
        logits, cache = core.forward(params, self.cfg, tokens, cache, offsets)
        b_idx = jnp.arange(B)
        tok0 = jnp.argmax(logits[b_idx, tlen - 1], axis=-1).astype(jnp.int32)
        if K == 1:
            return tok0[:, None], cache

        def step(carry, j):
            cache, cur = carry
            lg, cache = core.forward(
                params, self.cfg, cur[:, None], cache, offsets + tlen + j
            )
            nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
            return (cache, nxt), nxt

        (cache, _), rest = lax.scan(
            step, (cache, tok0), jnp.arange(K - 1, dtype=jnp.int32)
        )
        drafts = jnp.concatenate([tok0[:, None], rest.T], axis=1)
        return drafts, cache

    # --------------------------------------------------- Drafter interface
    def _slot(self, req) -> _Slot | None:
        st = self._slots.get(id(req))
        if st is None:
            if not self._free:
                return None
            st = _Slot(self._free.pop())
            self._slots[id(req)] = st
        return st

    def propose_batch(self, rows):
        out = {}
        active = []  # (b, req, st, ctx)
        for b, req in rows:
            ctx = list(req.ids) + list(req.out_ids)
            if len(ctx) > self.cap:
                out[b] = []              # past drafter capacity: a miss
                continue
            st = self._slot(req)
            if st is None:
                out[b] = []              # no cache row free (shouldn't
                continue                 # happen: batch == max_batch)
            if st.consumed > len(ctx) - 1 or st.consumed < 0:
                # context moved under us (stop-string truncation, slot
                # reuse): recompute from scratch — rewriting from 0 is
                # always sound, it re-establishes the contiguous frontier
                st.consumed = 0
            active.append((b, req, st, ctx))
        if not active:
            return out

        # -- catch-up: prime rows whose frontier trails the context tail.
        # Target frontier is len(ctx) - 1 (the last token feeds in the
        # draft chunk itself so its logits yield draft 0).
        while any(len(ctx) - 1 - st.consumed > 1 for _, _, st, ctx in active):
            tokens = np.zeros((self.batch, self.prime_width), np.int32)
            offsets = np.full((self.batch,), self._idle_off, np.int32)
            for _, _, st, ctx in active:
                n = min(self.prime_width, len(ctx) - 1 - st.consumed)
                if n <= 1:
                    continue
                chunk = ctx[st.consumed:st.consumed + self.prime_width]
                tokens[st.idx, :len(chunk)] = chunk
                offsets[st.idx] = st.consumed
                st.consumed += n
            self.cache = self._prime(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(offsets),
            )

        # -- the draft step proper: one [B, 2] root call for all rows
        tokens = np.zeros((self.batch, 2), np.int32)
        tlen = np.ones((self.batch,), np.int32)
        offsets = np.full((self.batch,), self._idle_off, np.int32)
        for _, _, st, ctx in active:
            tail = ctx[st.consumed:]
            tokens[st.idx, :len(tail)] = tail
            tlen[st.idx] = len(tail)
            offsets[st.idx] = st.consumed
            st.consumed = len(ctx)
        drafts_d, self.cache = self._draft(
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(tlen), jnp.asarray(offsets),
        )
        # meshlint: ignore[ML-J003] -- drafts feed the verify dispatch on
        # this same scheduler step; the readback IS the product
        drafts = np.asarray(jax.device_get(drafts_d))
        for b, _, st, _ in active:
            out[b] = [int(t) for t in drafts[st.idx]]
        return out

    def observe(self, req, accepted: int) -> None:
        st = self._slots.get(id(req))
        if st is None:
            return
        # drafts[0..accepted-1] were fed during the scan, so their K/V is
        # already valid context; a full accept's last draft (K-1) and the
        # bonus token were never fed — they arrive in the next chunk
        st.consumed += min(int(accepted), self.spec_tokens - 1)
        if accepted == 0:
            st.zero_streak += 1
            if st.zero_streak >= self.REPRIME_AFTER:
                st.consumed = 0          # full re-prime from prompt+accepted
                st.zero_streak = 0
        else:
            st.zero_streak = 0

    def forget(self, req) -> None:
        st = self._slots.pop(id(req), None)
        if st is not None:
            self._free.append(st.idx)

    def close(self) -> None:
        self._slots.clear()
        self._free = list(range(self.batch))
        self.params = None
        self.cache = None

    def hbm_source(self):
        """HBM ledger hook: the drafter's resident footprint."""
        return {"params": self.params, "cache": self.cache}
