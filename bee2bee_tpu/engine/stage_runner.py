"""StageRunner: executes one pipeline stage of a model on this node's mesh.

The worker-side half of cross-peer pipeline serving (BASELINE config 4).
A node loads layers [a, b) of a model (models/stages.py) and answers
part_forward requests: ids or hidden states in, hidden states or logits
out, with a per-request KV cache held between calls — the TPU-native
realization of the reference's partial-model worker (reference
node.py:236-277: HF_PART_LOAD builds a layer range, HF_PART_FORWARD feeds
text or received hidden states).

Design:
- One jit'd stage_forward per (T, cached?) shape — prefill (T=prompt
  bucket) and decode (T=1) each compile once; the cache is donated so XLA
  updates it in HBM.
- Caches are per request_id, created lazily at first forward and dropped
  on release() (or by the idle reaper when a coordinator vanishes).
- Thread-safe: gateways/mesh handlers call from executor threads; a lock
  guards the cache table only (jax dispatch is itself thread-safe).
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics import get_registry
from ..models import config as model_config
from ..models import core, stages

STALE_CACHE_S = 600.0  # drop request caches untouched this long

# serving forward time (jit dispatch + host readback), measured INSIDE
# the concurrency gate so queue/semaphore wait never inflates it: the
# digest p50 of this series is the "stage compute" the coordinator's
# microbatch auto-depth heuristic divides by (meshnet/pipeline.py
# resolve_microbatches; health.DIGEST_HISTOGRAMS carries it).
_H_STAGE_TASK_MS = get_registry().histogram(
    "pipeline.stage_task_ms",
    "stage forward compute + readback time (excludes queue wait)",
)


class StageRunner:
    def __init__(
        self,
        model: str | model_config.ModelConfig,
        n_stages: int,
        stage: int,
        params=None,  # FULL param tree (sliced here) — or None to random-init
        checkpoint_path: str | None = None,
        max_seq_len: int = 2048,
        dtype: str = "bfloat16",
        rng_seed: int = 0,
        max_batch: int = 8,
        quantize: str = "none",  # "int8": weight-only quant of THIS stage's
        # slice — a 7B half per peer is exactly where halved weight HBM pays
        stale_cache_s: float = STALE_CACHE_S,  # reap TTL for abandoned
        # request caches (failover tests shrink it; long-idle coordinators
        # raise it)
        epoch: int = 0,  # stage epoch (pipeline failover): tasks stamped
        # with a different epoch are rejected, so late traffic routed to a
        # replaced occupant can never corrupt the rebuilt chain
        max_concurrent_forwards: int = 4,  # concurrent jit dispatches this
        # stage will run: an interleaved coordinator free-runs one chain
        # per microbatch group, and without a bound a deep group fan-out
        # (or several coordinators sharing a worker) queues unbounded
        # compute on the device while earlier dispatches still hold HBM
        # scratch. Excess callers BLOCK on their executor thread — the
        # wire-level backpressure the coordinator's sliding window rides
    ):
        # same any-checkpoint rule as the engine
        # (`serve-stage --model auto --checkpoint <dir>`)
        self.model_cfg = model_config.resolve_model_config(model, checkpoint_path)
        # the mesh addresses runners by the COORDINATOR'S model string —
        # remember what the caller asked for so add_stage_runner can alias
        # it to the resolved config name
        self.requested_model = model if isinstance(model, str) else self.model_cfg.name
        self.spec = stages.StageSpec.build(self.model_cfg, n_stages, stage)
        self.dtype = jnp.dtype(dtype)
        self.max_seq_len = min(max_seq_len, self.model_cfg.max_seq_len)
        self.max_batch = max_batch
        self.stale_cache_s = float(stale_cache_s)
        self.epoch = int(epoch)
        # identity fields for matches_load (part_load idempotency): a
        # failover re-load of the SAME stage must be a no-op, not a rebuild
        self.checkpoint_path = checkpoint_path
        self.rng_seed = int(rng_seed)
        quantize = quantize or "none"  # accept ''/None like the engine does
        if quantize not in ("none", "int8"):
            raise ValueError(f"quantize={quantize!r}: only 'int8' or 'none'")
        self.quantize = quantize

        if params is None and checkpoint_path:
            from ..models.loader import load_checkpoint

            # quantizing: keep the load host-side so the dense model never
            # materializes in device memory (engine.py does the same)
            params = load_checkpoint(
                checkpoint_path, self.model_cfg, dtype=self.dtype,
                host=quantize == "int8",
            )
        if params is None:
            # deterministic random init: every stage of a pipeline derives
            # the SAME full tree from the seed, then keeps its slice — so
            # peers agree on weights without moving bytes (tests; real
            # deployments load a checkpoint or fetch pieces)
            params = core.init_params(
                self.model_cfg, jax.random.key(rng_seed), dtype=self.dtype
            )
        sliced = stages.extract_stage_params(params, self.model_cfg, self.spec)
        if quantize == "int8" or jax.default_backend() == "cpu":
            # host-side transforms, then ONE device upload (a 7B-class
            # slice making extra device round trips at part_load is real
            # time): int8 quantizes the slice; single-device CPU unstacks
            # layers into contiguous per-layer arrays (the XLA:CPU
            # packed-GEMM issue — core.forward / docs/PERF.md). TPU keeps
            # the stacked scan.
            host = jax.device_get(sliced)
            if quantize == "int8":
                from ..models.quant import quantize_params

                host = quantize_params(host)
            if jax.default_backend() == "cpu":
                host = core.unstack_layers(host)
            self.params = jax.tree.map(jnp.asarray, host)
        else:
            self.params = sliced

        def _wrapped(p, x, cache, off, mask, gather):
            out, c = stages.stage_forward(
                p, self.model_cfg, self.spec, x, cache, off, write_mask=mask
            )
            if gather is not None and self.spec.is_last:
                # per-row position pick: [B, T, V] -> [B, V]. Keeps a
                # session prefill from shipping bucket*V logits per row
                # over the wire when only one position per row matters.
                out = out[jnp.arange(out.shape[0]), jnp.asarray(gather, jnp.int32)]
            return out, c

        # retrace sentinel (engine/introspect.py, ISSUE 15): the stage
        # forward is THE pipeline worker's hot jit root — per-instance
        # sentinel (a fresh runner's compiles are its own warm-up), no
        # declared predicate (prefill widths come from the coordinator's
        # bucketing; any FIRST-seen shape is growth, repeats storm).
        from .introspect import RetraceSentinel

        self._sentinel = RetraceSentinel()
        self._fwd = self._sentinel.watch(
            "stage_forward",
            jax.jit(_wrapped, donate_argnums=(2,)),
            key_fn=lambda p, x, cache, off, mask, gather: (
                tuple(int(s) for s in x.shape),
                mask is not None, gather is not None,
            ),
        )
        self._caches: dict[str, dict] = {}  # request_id -> {"cache", "touched"}
        self._lock = threading.Lock()
        self.max_concurrent_forwards = max(1, int(max_concurrent_forwards))
        self._fwd_sem = threading.BoundedSemaphore(self.max_concurrent_forwards)

        # ---- cross-peer pipeline TRAINING (TPU-native realization of the
        # reference's layer_forward_train/layer_backward worker tasks,
        # reference node.py:99-182 — toy numpy MLP there; real stage VJP
        # + in-place SGD on the stage's own params here) ----
        # all dtype casts live INSIDE the jitted fns: an eager astype is a
        # blocking round trip per call on a tunneled chip (see memory/PERF)
        out_dtype = jnp.float32 if self.spec.is_last else self.dtype

        def _fwd_train_raw(p, x):
            out, _ = stages.stage_forward(p, self.model_cfg, self.spec, x, None, 0)
            return out

        def _fwd_train(p, x):
            return _fwd_train_raw(p, x).astype(out_dtype)

        def _bwd(p, x, dy):
            if self.spec.is_first:  # x is int ids: no gradient flows to it
                out, vjp = jax.vjp(lambda p_: _fwd_train_raw(p_, x), p)
                (dp,) = vjp(dy.astype(out.dtype))
                return dp, None
            out, vjp = jax.vjp(_fwd_train_raw, p, x)
            dp, dx = vjp(dy.astype(out.dtype))
            return dp, dx.astype(self.dtype)

        def _sgd(p, dp, lr):
            return jax.tree.map(lambda w, g: w - lr * g.astype(w.dtype), p, dp)

        self._fwd_train = jax.jit(_fwd_train)
        self._bwd = jax.jit(_bwd)
        # NO donation: a concurrent inference forward may hold the old
        # param tree mid-dispatch (serve + train share the runner);
        # donating would delete buffers out from under it
        self._sgd = jax.jit(_sgd)
        self._train_acts: dict[str, dict] = {}  # request_id -> {"x", "touched"}

    # ------------------------------------------------------------------ API

    @property
    def info(self) -> dict:
        return {
            "model": self.model_cfg.name,
            "n_stages": self.spec.n_stages,
            "stage": self.spec.stage,
            "layers": [self.spec.start, self.spec.end],
            "is_first": self.spec.is_first,
            "is_last": self.spec.is_last,
            "max_seq_len": self.max_seq_len,
            # observable over the wire (part_load RESULT): a coordinator
            # can CONFIRM its stages quantized, not just request it
            "quantize": self.quantize,
            # a worker that outlived a coordinator restart reports the
            # epoch it is at; the coordinator adopts the max and re-loads
            "epoch": self.epoch,
            # stage-side concurrency cap: how many chains this worker
            # will run at once (the interleaved session's window should
            # not be sized past the fleet's smallest cap)
            "max_concurrent_forwards": self.max_concurrent_forwards,
        }

    def matches_load(self, data: dict) -> bool:
        """Does a part_load request describe THIS runner? Same model
        identity, partition, weights source, and serving shape — epoch
        excluded on purpose: an epoch bump ADOPTS the runner (no-op
        re-load, relay links re-dialed) instead of recompiling it."""
        model = data.get("model")
        try:
            dtype_match = jnp.dtype(data.get("dtype", "bfloat16")) == self.dtype
        except TypeError:
            return False
        return (
            model in (self.requested_model, self.model_cfg.name)
            and int(data.get("n_stages", -1)) == self.spec.n_stages
            and int(data.get("stage", -1)) == self.spec.stage
            and (data.get("checkpoint_path") or None) == self.checkpoint_path
            and int(data.get("rng_seed", 0)) == self.rng_seed
            and dtype_match
            and min(int(data.get("max_seq_len", 2048)),
                    self.model_cfg.max_seq_len) == self.max_seq_len
            and (data.get("quantize") or "none") == self.quantize
        )

    def forward(
        self,
        request_id: str,
        x: np.ndarray,
        offset,  # int | [B] int array — per-row write positions
        write_mask=None,  # [B] bool — rows whose cache this call updates
        gather=None,  # [B] int — last stage returns logits[b, gather[b]] only
    ) -> np.ndarray:
        """Run a chunk through this stage against the request's cache.

        x: [B, T] int ids on the first stage, [B, T, D] hidden later.
        Returns hidden [B, T, D] (f32) or logits [B, T, V] (f32, last).

        A batched pipeline session passes offset as a [B] vector (each row
        decodes at its own depth) and write_mask to admit one row's prefill
        without touching live rows (meshnet/pipeline.PipelineSession)."""
        if self.spec.is_first:
            xj = jnp.asarray(x, jnp.int32)
            B = xj.shape[0]
        else:
            xj = jnp.asarray(x, self.dtype)
            B = xj.shape[0]
        with self._lock:
            self._reap_stale()
            entry = self._caches.get(request_id)
            if entry is None:
                if len(self._caches) >= self.max_batch:
                    raise RuntimeError(
                        f"stage cache table full ({self.max_batch} requests)"
                    )
                entry = {
                    "cache": stages.init_stage_cache(
                        self.model_cfg, self.spec, B, self.max_seq_len, self.dtype
                    ),
                    "touched": time.time(),
                }
                self._caches[request_id] = entry
            cache = entry["cache"]
            if cache is None:
                # a second in-flight forward for the same request would
                # otherwise run uncached (None) and silently diverge
                raise RuntimeError(f"concurrent forward for request {request_id!r}")
            entry["cache"] = None  # donated below; never leave a stale ref
        off = jnp.asarray(np.asarray(offset, np.int32))
        mask = None if write_mask is None else jnp.asarray(np.asarray(write_mask, bool))
        gat = (
            None
            if (gather is None or not self.spec.is_last)
            else jnp.asarray(np.asarray(gather, np.int32))
        )
        try:
            with self._fwd_sem:
                t0 = time.perf_counter()
                out, cache = self._fwd(self.params, xj, cache, off, mask, gat)
        except Exception:
            # free the slot: leaving the None entry would burn a max_batch
            # row for stale_cache_s and turn retries into misleading
            # "concurrent forward" errors
            with self._lock:
                self._caches.pop(request_id, None)
            raise
        with self._lock:
            if request_id in self._caches:  # release() may have raced us
                self._caches[request_id] = {"cache": cache, "touched": time.time()}
        # logits stay f32 (sampling precision); hidden states cross the wire
        # in the compute dtype (bf16 halves inter-peer bandwidth, the
        # stages.py design point)
        if self.spec.is_last:
            host = np.asarray(jax.device_get(out), np.float32)
        else:
            host = np.asarray(jax.device_get(out.astype(self.dtype)))
        _H_STAGE_TASK_MS.observe((time.perf_counter() - t0) * 1000.0)
        return host

    # ----------------------------------------------------------- training

    def forward_train(self, request_id: str, x: np.ndarray) -> np.ndarray:
        """Uncached full forward, retaining this stage's input for the
        matching backward (one in-flight microbatch per request_id).
        Abandoned retentions are reaped with the stale caches."""
        if self.quantize != "none":
            raise RuntimeError(
                "training through a quantized stage is unsupported "
                "(gradients w.r.t. int8 payloads are meaningless)"
            )
        x_host = np.asarray(x, np.int32 if self.spec.is_first else None)
        with self._lock:
            self._reap_stale()
            self._train_acts[request_id] = {"x": x_host, "touched": time.time()}
        out = self._fwd_train(self.params, x_host)
        return np.asarray(jax.device_get(out))

    def backward(self, request_id: str, dy: np.ndarray, lr: float) -> np.ndarray | None:
        """VJP against the retained activation; SGD-update this stage's
        params; return dX for the previous stage (None on the first stage
        — ids take no gradient). Cotangent/output casts happen inside the
        jitted _bwd (dtype bookkeeping is compiled, not eager)."""
        with self._lock:
            entry = self._train_acts.pop(request_id, None)
        if entry is None:
            raise RuntimeError(f"no retained forward for request {request_id!r}")
        dp, dx = self._bwd(self.params, entry["x"], np.asarray(dy))
        self.params = self._sgd(self.params, dp, np.float32(lr))
        if dx is None:
            return None
        return np.asarray(jax.device_get(dx))

    def release(self, request_id: str) -> None:
        with self._lock:
            self._caches.pop(request_id, None)
            self._train_acts.pop(request_id, None)

    def _reap_stale(self) -> None:
        now = time.time()
        for table in (self._caches, self._train_acts):
            dead = [
                rid for rid, e in table.items()
                if now - e["touched"] > self.stale_cache_s
            ]
            for rid in dead:
                table.pop(rid, None)

    @property
    def active_requests(self) -> int:
        with self._lock:
            return len(self._caches)
