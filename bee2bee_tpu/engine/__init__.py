"""Inference engine: jit-compiled prefill/decode over a paged KV block
pool, bucketed shapes, on-device sampling, and token streaming. This is the
TPU-native replacement for the reference's torch `model.generate` thread
(reference hf.py:84-108)."""

from .engine import EngineConfig, GenerationResult, InferenceEngine  # noqa: F401
from .sampling import sample  # noqa: F401
