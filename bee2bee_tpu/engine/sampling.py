"""On-device token sampling: greedy / temperature / top-k / top-p / min-p.

Replaces the sampling knobs the reference forwards to torch generate
(reference services.py:44-59: temperature, max_new_tokens). Everything is
shape-static and branchless via masking, so it lives inside the jit'd
decode step — no host round-trip between logits and token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample(
    logits,  # [B, V] float32
    key,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    min_p: float = 0.0,
):
    """Sample next tokens [B]. temperature<=0 → greedy (argmax).

    Static Python values for the knobs keep the jitted step monomorphic —
    the engine compiles one step per (temperature==0?) variant, which is
    the right trade: sampling params rarely change within a request.
    """
    if temperature is None or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)

    logits = logits / jnp.asarray(max(temperature, 1e-6), logits.dtype)

    if min_p and min_p > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        floor = min_p * jnp.max(probs, axis=-1, keepdims=True)
        logits = jnp.where(probs >= floor, logits, -jnp.inf)

    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)

    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p; the top
        # token is always kept (top_p=0 degrades to greedy, not to garbage)
        keep = (cum - probs < top_p).at[:, 0].set(True)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)

    return jax.random.categorical(key, logits, axis=-1)


def apply_penalties(
    logits,  # [B, V] float32
    counts,  # [B, 2, V] int32: [:, 0] prompt occurrences, [:, 1] generated
    repetition,  # [B] float32; 1.0 = off (HF-style multiplicative)
    presence,  # [B] float32; 0.0 = off (flat tax on any generated token)
    frequency,  # [B] float32; 0.0 = off (per-generated-occurrence tax)
):
    """Occurrence penalties, applied BEFORE temperature/argmax so greedy
    decoding benefits too (greedy + repetition_penalty is the classic
    'stop the loop' config). The two count channels carry the two
    conventions faithfully: repetition follows HF's
    RepetitionPenaltyLogitsProcessor (divide positive logits, multiply
    negative ones, over PROMPT + generated tokens); presence/frequency
    follow OpenAI (generated tokens ONLY — taxing prompt words would
    make a summarizer avoid its own article's subject)."""
    gen = counts[:, 1]
    seen_any = (counts[:, 0] > 0) | (gen > 0)
    rep = repetition[:, None]
    logits = jnp.where(
        seen_any, jnp.where(logits > 0, logits / rep, logits * rep), logits
    )
    logits = logits - presence[:, None] * (gen > 0).astype(logits.dtype)
    logits = logits - frequency[:, None] * gen.astype(logits.dtype)
    return logits


def sample_batched(
    logits,  # [B, V] float32
    key,
    temperature,  # [B] float32; <= 0 → greedy for that row
    top_k,  # [B] int32; <= 0 → no top-k restriction
    top_p,  # [B] float32; >= 1 → no nucleus restriction
    min_p=None,  # [B] float32; <= 0 → off. Keeps tokens whose prob (after
    # temperature) is >= min_p * max prob — a relative floor that adapts
    # to the distribution's confidence where top_p's absolute mass cut
    # does not (the "min-p sampling" recipe)
    counts=None,  # optional [B, 2, V] int32 (see apply_penalties) → penalties first
    repetition=None,  # [B] float32 (with counts)
    presence=None,  # [B] float32 (with counts)
    frequency=None,  # [B] float32 (with counts)
):
    """Per-row sampling for continuous batching: every knob is a traced
    [B] array, so ONE compiled decode step serves any mix of concurrent
    requests' sampling settings (the scalar `sample` compiles one variant
    per signature — fine for a single stream, wrong for a shared batch).

    This is the sampling stage of the FUSED decode root (scheduler
    ._decode_fn and engine._spec_verify_fn call it inside their jit
    graphs, threading ``counts`` through the scan carry): logits never
    leave the device between the forward and the token, and a penalized
    row rides the same compiled window as its greedy neighbors instead
    of parking the whole batch on a split counts graph. ``counts=None``
    lowers to a counts-free graph — the pre-fusion trace, bit-for-bit —
    which is what an all-plain batch compiles and runs.

    Semantics per row match `sample`: [penalties →] temperature scale →
    top-k mask → nucleus mask over the already-masked logits →
    categorical; greedy rows short-circuit to argmax via a final where.
    """
    if counts is not None:
        logits = apply_penalties(logits, counts, repetition, presence, frequency)
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)

    def sampled_path(_):
        l = logits / jnp.maximum(temperature, 1e-6)[:, None]

        if min_p is not None:
            probs0 = jax.nn.softmax(l, axis=-1)
            floor = min_p[:, None] * jnp.max(probs0, axis=-1, keepdims=True)
            # the top token always survives (probs0 >= floor there)
            l = jnp.where(probs0 >= floor, l, -jnp.inf)

        sorted_l = jnp.sort(l, axis=-1)[:, ::-1]
        k_eff = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
        kth = jnp.take_along_axis(sorted_l, (k_eff - 1)[:, None], axis=-1)
        l = jnp.where(l < kth, -jnp.inf, l)

        sorted_m = jnp.sort(l, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_m, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs < top_p[:, None]).at[:, 0].set(True)
        cutoff = jnp.min(
            jnp.where(keep, sorted_m, jnp.inf), axis=-1, keepdims=True
        )
        l = jnp.where(l < cutoff, -jnp.inf, l)

        sampled = jax.random.categorical(key, l, axis=-1)
        return jnp.where(temperature <= 0.0, greedy, sampled)

    # the sorts/cumsum above cost real time at vocab scale (two bitonic
    # sorts of [B, V] per token on TPU); an all-greedy batch — the common
    # serving default — must pay argmax only. lax.cond executes one branch.
    return jax.lax.cond(
        jnp.any(temperature > 0.0), sampled_path, lambda _: greedy, None
    )
