"""Speculative decoding drafters: a tiered stack behind one interface.

Single-stream decode pays one full forward pass per token — the latency
floor interactive clients feel. Speculative decoding breaks it: draft up
to K tokens cheaply, then verify all K in ONE [B, K+1] forward
(engine.InferenceEngine._spec_verify_fn) and accept the longest exact
prefix. On a weight-bound chip that forward costs about the same as a
single decode step, so every accepted draft token is a free step.

Three draft TIERS share the ``Drafter`` interface, selected PER ROW by
the scheduler with the same gating discipline spec decode always used
(``DrafterStack`` picks the tier; ``should_disable`` — unchanged math —
decides when a row's current tier has failed its probe):

- ``ngram``: zero-cost host-side prompt lookup (``find_ngram_draft``) —
  matches the sequence's own tail against its earlier content. Free, but
  acceptance collapses to ~0 on non-repetitive chat traffic.
- ``model``: a real small model resident beside the target
  (engine/drafter.py ``DraftModel``) drafting K tokens per eligible row
  in one batched autoregressive pass with its own tiny KV state.
- ``mesh``: the same model drafter hosted on a CHEAP PEER
  (``BEE2BEE_DISAGG=draft``; meshnet/draft.py). Drafts stream over
  draft_request/draft_result frames, pipelined one step ahead so the
  draft RTT hides under the target's decode step. ``MeshDrafter`` here
  is the transport-agnostic scheduler side: a not-yet-arrived draft is
  PENDING (the row simply doesn't draft this step — never a stall), a
  timed-out one is a miss, and a dead peer flips ``dead`` so the
  scheduler demotes every mesh row to the local tier, typed.

Rows move between tiers instead of dying: when a tier fails its probe
budget the row DEMOTES down the ladder (mesh → model → ngram → off) —
or ESCALATES from ngram to a model-class tier when one is configured,
so a row whose content stops repeating still profits from the model.

Why rollback is free: the verify chunk writes K/V for positions
[offset, offset+K+1), but the row's offset only advances by accepted+1.
Rejected positions are >= the new offset, and the engine's causal
invariant — any cache position >= the write offset is either masked at
read time or overwritten before attention sees it — already guarantees
stale K/V there is never observed (the same invariant that makes the
paged cache's CoW prefix sharing sound; see engine/paged.py).

Everything in this module is host-side python/numpy owned by the
scheduler thread (MeshDrafter additionally takes results from the
transport thread under a lock); nothing here is jit-traced. The model
drafter's jit roots live in engine/drafter.py, the verify root in
engine/engine.py, and the per-row gating in engine/scheduler.py (greedy
non-penalized rows speculate; sampled/penalized rows ride the existing
decode windows).
"""

from __future__ import annotations

import threading
import time

import numpy as np

# Tier vocabulary, cost-descending. Demotion walks RIGHT (cheaper);
# escalation from ngram picks the best model-class tier present. "off"
# is the terminal state when every configured tier has failed its probe
# — it is a row state, not a drafter.
TIER_LADDER = ("mesh", "model", "ngram")
TIER_OFF = "off"


def find_ngram_draft(
    context,
    k: int,
    min_match: int = 2,
    max_match: int = 8,
) -> list[int]:
    """Draft up to `k` tokens by longest-suffix n-gram lookup.

    Tries suffix lengths from min(max_match, len-1) down to min_match:
    the first (longest) n-gram whose most recent earlier occurrence is
    found wins, and the draft is the tokens that followed that
    occurrence. Returns [] when no suffix of length >= min_match repeats
    — the caller falls back to plain decode for this row/step.

    Matching the LONGEST suffix first maximizes draft quality (a longer
    shared context predicts the continuation better). Among occurrences
    of that suffix, the most recent one with a FULL k tokens of
    continuation wins — recency biases toward the sequence's current
    phase, but a naively-latest occurrence of a short-period repetition
    overlaps the tail and leaves almost nothing to draft (an all-same-
    token run would draft length 1 forever); only when no occurrence has
    full room does the earliest — longest partial continuation — serve.
    """
    n_ctx = len(context)
    if k < 1 or n_ctx < min_match + 1:
        return []
    arr = np.asarray(context, dtype=np.int64)
    for n in range(min(max_match, n_ctx - 1), min_match - 1, -1):
        pattern = arr[n_ctx - n:]
        # candidate starts [0, n_ctx - n): every one has >= 1 token
        # following its window; position n_ctx - n is the suffix itself
        windows = np.lib.stride_tricks.sliding_window_view(arr, n)[:n_ctx - n]
        hits = np.flatnonzero((windows == pattern).all(axis=1))
        if hits.size:
            roomy = hits[hits + n + k <= n_ctx]
            start = int(roomy[-1] if roomy.size else hits[0]) + n
            return arr[start:start + k].tolist()
    return []


def should_disable(
    drafted: int, accepted: int, probe_tokens: int, min_rate: float
) -> bool:
    """Per-row probe verdict: True once the row has drafted at least
    `probe_tokens` tokens ON ITS CURRENT TIER with acceptance below
    `min_rate`. The row's tier has proven useless for this content — the
    scheduler moves it to the next tier on the ladder (or off when none
    remain). Counters reset per tier, so each tier gets its own probe
    budget; a failed tier is never retried for that row (requests are
    short-lived; there is no re-enable)."""
    return drafted >= probe_tokens and accepted < min_rate * drafted


class Drafter:
    """One draft tier. The scheduler talks to every tier through this
    interface and keys per-row tier choice off ``tier``.

    propose_batch() maps row slot -> draft for all rows currently
    assigned to this tier:

    - a token list  = a draft to verify (may be shorter than K),
    - []            = a miss this step (counts against the probe budget),
    - None          = PENDING (mesh tier only): the draft hasn't arrived
                      yet; the row skips drafting this step with NO
                      accounting — pending is not failure.

    observe()/forget() let stateful tiers (model KV, mesh pipeline) roll
    forward on accept and release per-request state at retirement; the
    stateless n-gram tier inherits the no-ops.
    """

    tier = "?"
    spec_tokens = 0

    def propose_batch(self, rows):
        raise NotImplementedError

    def observe(self, req, accepted: int) -> None:  # noqa: ARG002
        """Verify verdict for a row this tier drafted: `accepted` of the
        proposed tokens were kept (plus the bonus token)."""

    def forget(self, req) -> None:  # noqa: ARG002
        """Release any per-request state (row retired or left the tier)."""

    def close(self) -> None:
        """Release tier-wide resources (weights, transport)."""


class NgramDrafter(Drafter):
    """Tier "ngram": drafting policy object the scheduler holds —
    configuration plus the propose() entry point. Stateless across
    rows/steps — per-row acceptance bookkeeping lives on the Request
    (spec_tier / spec_tier_drafted / spec_tier_accepted)."""

    tier = "ngram"

    def __init__(
        self,
        spec_tokens: int,
        min_match: int = 2,
        max_match: int = 8,
    ):
        if spec_tokens < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
        if not (1 <= min_match <= max_match):
            raise ValueError(
                f"need 1 <= min_match <= max_match, got "
                f"{min_match}..{max_match}"
            )
        self.spec_tokens = spec_tokens
        self.min_match = min_match
        self.max_match = max_match

    def propose(self, prompt_ids, out_ids) -> list[int]:
        """Draft for one row from its OWN prompt + generated ids."""
        return find_ngram_draft(
            list(prompt_ids) + list(out_ids),
            self.spec_tokens,
            self.min_match,
            self.max_match,
        )

    def propose_batch(self, rows):
        return {b: self.propose(req.ids, req.out_ids) for b, req in rows}


class _MeshRow:
    """Per-request pipeline state for the mesh tier (client side)."""

    __slots__ = ("rid", "ctx_sent", "inflight_pos", "deadline",
                 "ready_pos", "ready_draft", "failures")

    def __init__(self, rid: str):
        self.rid = rid
        self.ctx_sent = 0          # ctx tokens the peer has appended
        self.inflight_pos = -1     # ctx length the outstanding request drafts at
        self.deadline = 0.0
        self.ready_pos = -1        # ctx length the received draft was computed at
        self.ready_draft = None
        self.failures = 0          # consecutive timeouts/errors


class MeshDrafter(Drafter):
    """Tier "mesh": client side of the remote draft peer, transport-
    agnostic. meshnet/draft.py attaches a ``send(payload) -> bool``
    callable and forwards draft_result frames into deliver(); this class
    owns the pipelining, timeout, and degradation policy so the
    scheduler never blocks on the network:

    - PIPELINED ONE AHEAD: observe() (the verify verdict) immediately
      ships the accepted delta and requests the NEXT draft, so the RTT
      runs concurrently with the target's own next decode/verify step.
      propose_batch() only CONSUMES results that already arrived.
    - PENDING != MISS: a result not yet arrived returns None (row skips
      drafting this step, zero accounting). Only a passed deadline is a
      miss — it counts against the probe budget and triggers a full
      re-send (base=0), so a dropped frame self-heals.
    - TYPED DEATH: `max_failures` consecutive timeouts/errors, a send
      into a void, or an explicit peer-lost notice flip ``dead`` with a
      reason in {"timeout", "peer_lost", "no_peer"}; the scheduler
      demotes every mesh row to the local tier and never comes back.

    The wire protocol (draft_request/draft_result, declared in
    analysis/schema.py) is documented on meshnet/draft.py.
    """

    tier = "mesh"

    def __init__(
        self,
        spec_tokens: int,
        model: str = "",
        timeout_s: float = 2.0,
        max_failures: int = 3,
    ):
        if spec_tokens < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
        self.spec_tokens = spec_tokens
        self.model = model
        self.timeout_s = timeout_s
        self.max_failures = max_failures
        self.dead = False
        self.dead_reason = None
        self._send = None          # callable(payload: dict) -> bool
        self._lock = threading.Lock()
        self._rows: dict[int, _MeshRow] = {}   # id(req) -> state
        self._by_rid: dict[str, _MeshRow] = {}
        self._next_rid = 0

    # -- transport attachment (called by meshnet/draft.py) ---------------
    def attach_transport(self, send_fn) -> None:
        with self._lock:
            self._send = send_fn

    def peer_lost(self) -> None:
        """Transport tells us the draft peer died/disconnected."""
        self._mark_dead("peer_lost")

    def _mark_dead(self, reason: str) -> None:
        with self._lock:
            if not self.dead:
                self.dead = True
                self.dead_reason = reason

    # -- wire helpers (lock held) ----------------------------------------
    def _submit(self, st: _MeshRow, ctx, full: bool) -> bool:
        if self._send is None:
            self.dead, self.dead_reason = True, "no_peer"
            return False
        base = 0 if full else st.ctx_sent
        payload = {
            "rid": st.rid,
            "base": base,
            "tokens": [int(t) for t in ctx[base:]],
            "k": self.spec_tokens,
            "model": self.model,
        }
        ok = False
        try:
            ok = bool(self._send(payload))
        except Exception:
            ok = False
        if not ok:
            self.dead, self.dead_reason = True, "no_peer"
            return False
        st.ctx_sent = len(ctx)
        st.inflight_pos = len(ctx)
        st.deadline = time.monotonic() + self.timeout_s
        return True

    def _row(self, req) -> _MeshRow:
        st = self._rows.get(id(req))
        if st is None:
            rid = f"d{self._next_rid}"
            self._next_rid += 1
            st = _MeshRow(rid)
            self._rows[id(req)] = st
            self._by_rid[rid] = st
        return st

    # -- Drafter interface (scheduler thread) ----------------------------
    def propose_batch(self, rows):
        out = {}
        now = time.monotonic()
        with self._lock:
            for b, req in rows:
                if self.dead:
                    out[b] = []
                    continue
                st = self._row(req)
                ctx = list(req.ids) + list(req.out_ids)
                ctx_len = len(ctx)
                miss = False
                if st.ready_pos >= 0 and st.ready_pos != ctx_len:
                    # CATCH-UP: the row advanced (a plain decode window
                    # ran while the draft was in flight — pending rows
                    # never stall). The draft predicted the tokens from
                    # its own position; if its prefix matches what the
                    # row actually produced since, the TAIL is still a
                    # valid draft for the current position. A mismatched
                    # prefix means the drafter mispredicted those tokens
                    # — a real miss that must feed the tier's probe, or
                    # a bad mesh drafter could ride pending/stale cycles
                    # forever without ever failing its audition.
                    delta = ctx_len - st.ready_pos
                    draft = st.ready_draft or []
                    if 0 < delta < len(draft) and (
                        draft[:delta] == ctx[st.ready_pos:]
                    ):
                        st.ready_pos = ctx_len
                        st.ready_draft = draft[delta:]
                    else:
                        # a fully-outpaced draft whose tokens all matched
                        # what the row produced is NOT a miss — the
                        # drafter was right, just slower than the plain
                        # decode windows; penalizing it would fail the
                        # probe on latency, not accuracy
                        correct = delta > 0 and (
                            draft
                            == ctx[st.ready_pos:st.ready_pos + len(draft)]
                        )
                        st.ready_pos, st.ready_draft = -1, None
                        miss = delta > 0 and not correct
                if st.ready_pos == ctx_len:
                    out[b] = st.ready_draft or []
                    st.ready_pos, st.ready_draft = -1, None
                    continue
                if st.inflight_pos < 0:
                    # first contact for this row (or a consumed/dropped
                    # result with no observe since): prime the pipeline
                    self._submit(st, ctx, full=st.ctx_sent == 0)
                    out[b] = [] if miss else None
                elif now > st.deadline:
                    st.failures += 1
                    if st.failures >= self.max_failures:
                        self.dead, self.dead_reason = True, "timeout"
                        out[b] = []
                    else:
                        self._submit(st, ctx, full=True)
                        out[b] = []          # a timeout is a real miss
                else:
                    out[b] = [] if miss else None  # in flight: only the
                    # mispredicted-prefix drop above counts against the
                    # probe; a merely-pending draft is free
        return out

    def observe(self, req, accepted: int) -> None:
        # the verify verdict grew the context: pipeline the next draft
        # now so it overlaps the target's next step
        with self._lock:
            if self.dead:
                return
            st = self._rows.get(id(req))
            if st is None:
                return
            ctx = list(req.ids) + list(req.out_ids)
            self._submit(st, ctx, full=st.ctx_sent > len(ctx))

    def deliver(self, msg: dict) -> None:
        """draft_result frame from the transport thread."""
        with self._lock:
            st = self._by_rid.get(str(msg.get("rid", "")))
            if st is None:
                return
            if msg.get("error"):
                st.failures += 1
                st.inflight_pos = -1
                if st.failures >= self.max_failures:
                    self.dead, self.dead_reason = True, "peer_lost"
                return
            if msg.get("reprime"):
                # peer lost our delta baseline (restart/eviction): the
                # next submit re-sends the full context
                st.ctx_sent = 0
                st.inflight_pos = -1
                return
            pos = int(msg.get("pos", -1))
            if pos != st.inflight_pos:
                return                        # stale result: drop
            st.failures = 0
            st.inflight_pos = -1
            st.ready_pos = pos
            st.ready_draft = [int(t) for t in (msg.get("draft") or [])]

    def forget(self, req) -> None:
        with self._lock:
            st = self._rows.pop(id(req), None)
            if st is None:
                return
            self._by_rid.pop(st.rid, None)
            if self._send is not None and not self.dead:
                try:
                    self._send({"rid": st.rid, "done": True})
                except Exception:
                    pass

    def close(self) -> None:
        with self._lock:
            self._rows.clear()
            self._by_rid.clear()
            self._send = None


class DrafterStack:
    """The scheduler's one handle on all configured tiers.

    Holds a tier-name -> Drafter map (any subset of TIER_LADDER) and the
    tier-transition policy. Per-row tier state lives on the Request
    (spec_tier + spec_tiers_failed); this object is shared and
    stateless across rows.
    """

    def __init__(self, tiers: dict, spec_tokens: int):
        if not tiers:
            raise ValueError("DrafterStack needs at least one tier")
        for name in tiers:
            if name not in TIER_LADDER:
                raise ValueError(f"unknown draft tier {name!r}")
        self.tiers = tiers
        self.spec_tokens = spec_tokens

    def start_tier(self) -> str:
        """New rows start on the CHEAPEST configured tier (n-gram when
        present): it costs nothing to probe, and escalation to the model
        tiers is exactly the failure path the ladder encodes."""
        for name in reversed(TIER_LADDER):
            if name in self.tiers and self._alive(name):
                return name
        return TIER_OFF

    def _alive(self, name: str) -> bool:
        return not getattr(self.tiers[name], "dead", False)

    def next_tier(self, current: str, failed) -> str:
        """Where a row goes when `current` fails its probe (or dies).

        Demotion prefers tiers BELOW current on the ladder (cheaper);
        when none remain, escalate to an untried tier ABOVE (this is the
        n-gram -> model escalation: ngram is the ladder's floor, so its
        only exits are up or off). Tiers in `failed` are never retried.
        """
        try:
            i = TIER_LADDER.index(current)
        except ValueError:
            i = -1
        below = TIER_LADDER[i + 1:]
        above = TIER_LADDER[:max(i, 0)]
        for name in tuple(below) + tuple(reversed(above)):
            if name in self.tiers and name not in failed and self._alive(name):
                return name
        return TIER_OFF

    def forget(self, req) -> None:
        for d in self.tiers.values():
            d.forget(req)

    def close(self) -> None:
        for d in self.tiers.values():
            d.close()
