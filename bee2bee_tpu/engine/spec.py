"""Self-speculative decoding: host-side n-gram drafting (prompt lookup).

Single-stream decode pays one full forward pass per token — the latency
floor interactive clients feel. Speculative decoding breaks it WITHOUT a
second model: draft up to K tokens by matching the sequence's own tail
against its earlier content (chat transcripts, code and RAG contexts are
highly self-repetitive), then verify all K in ONE [B, K+1] forward
(engine.InferenceEngine._spec_verify_fn) and accept the longest exact
prefix. On a weight-bound chip that forward costs about the same as a
single decode step, so every accepted draft token is a free step.

Why rollback is free: the verify chunk writes K/V for positions
[offset, offset+K+1), but the row's offset only advances by accepted+1.
Rejected positions are >= the new offset, and the engine's causal
invariant — any cache position >= the write offset is either masked at
read time or overwritten before attention sees it — already guarantees
stale K/V there is never observed (the same invariant that makes the
paged cache's CoW prefix sharing sound; see engine/paged.py).

The drafter is pure host-side python/numpy owned by the scheduler
thread; nothing here is jit-traced. The device side lives in
engine/engine.py (the verify jit root) and the per-row gating in
engine/scheduler.py (greedy non-penalized rows speculate; sampled/
penalized rows ride the existing decode windows).
"""

from __future__ import annotations

import numpy as np


def find_ngram_draft(
    context,
    k: int,
    min_match: int = 2,
    max_match: int = 8,
) -> list[int]:
    """Draft up to `k` tokens by longest-suffix n-gram lookup.

    Tries suffix lengths from min(max_match, len-1) down to min_match:
    the first (longest) n-gram whose most recent earlier occurrence is
    found wins, and the draft is the tokens that followed that
    occurrence. Returns [] when no suffix of length >= min_match repeats
    — the caller falls back to plain decode for this row/step.

    Matching the LONGEST suffix first maximizes draft quality (a longer
    shared context predicts the continuation better). Among occurrences
    of that suffix, the most recent one with a FULL k tokens of
    continuation wins — recency biases toward the sequence's current
    phase, but a naively-latest occurrence of a short-period repetition
    overlaps the tail and leaves almost nothing to draft (an all-same-
    token run would draft length 1 forever); only when no occurrence has
    full room does the earliest — longest partial continuation — serve.
    """
    n_ctx = len(context)
    if k < 1 or n_ctx < min_match + 1:
        return []
    arr = np.asarray(context, dtype=np.int64)
    for n in range(min(max_match, n_ctx - 1), min_match - 1, -1):
        pattern = arr[n_ctx - n:]
        # candidate starts [0, n_ctx - n): every one has >= 1 token
        # following its window; position n_ctx - n is the suffix itself
        windows = np.lib.stride_tricks.sliding_window_view(arr, n)[:n_ctx - n]
        hits = np.flatnonzero((windows == pattern).all(axis=1))
        if hits.size:
            roomy = hits[hits + n + k <= n_ctx]
            start = int(roomy[-1] if roomy.size else hits[0]) + n
            return arr[start:start + k].tolist()
    return []


def should_disable(
    drafted: int, accepted: int, probe_tokens: int, min_rate: float
) -> bool:
    """Per-row adaptive disable: True once the row has drafted at least
    `probe_tokens` tokens with acceptance below `min_rate`. A row whose
    content stops repeating pays the draft lookup and the wider verify
    forward for nothing — after the probe budget, it drops back to plain
    decode for the rest of its life (requests are short-lived; there is
    no re-enable)."""
    return drafted >= probe_tokens and accepted < min_rate * drafted


class NgramDrafter:
    """Drafting policy object the scheduler holds: configuration plus the
    propose() entry point. Stateless across rows/steps — per-row
    acceptance bookkeeping lives on the Request (spec_drafted /
    spec_accepted / spec_disabled)."""

    def __init__(
        self,
        spec_tokens: int,
        min_match: int = 2,
        max_match: int = 8,
    ):
        if spec_tokens < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
        if not (1 <= min_match <= max_match):
            raise ValueError(
                f"need 1 <= min_match <= max_match, got "
                f"{min_match}..{max_match}"
            )
        self.spec_tokens = spec_tokens
        self.min_match = min_match
        self.max_match = max_match

    def propose(self, prompt_ids, out_ids) -> list[int]:
        """Draft for one row from its OWN prompt + generated ids."""
        return find_ngram_draft(
            list(prompt_ids) + list(out_ids),
            self.spec_tokens,
            self.min_match,
            self.max_match,
        )
