"""InferenceEngine: the serving core.

TPU-first structure (SURVEY §7 step 2, hard part 1):

- **Bucketed prefill**: prompts pad up to a power-of-two bucket; each bucket
  shape compiles once, bounding the recompile space. Pad K/V written past the
  true length is overwritten by decode exactly when it would enter the
  causal window, so no separate validity mask is needed.
- **Fixed-capacity KV cache** allocated once per request batch at
  max_seq_len, donated through every decode step so XLA updates it in place
  in HBM.
- **On-device sampling** inside the jit'd step: one fused
  forward+sample+cache-update program per token; the only host transfer per
  step is the sampled token id (needed for streaming/stop anyway).
- **Mesh-agnostic**: params and cache carry NamedShardings from
  models.partition; the same engine serves a 1-chip node or a v5e-8 TP
  group — jit inserts the collectives.

The generate() contract mirrors what the reference's streaming path provides
(reference hf.py:46-136: max_new_tokens, temperature, stop handling, chunk
callback) minus the transcript parsing, which lives in the service layer.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import config as model_config
from ..models import core, partition
from ..parallel.mesh import local_mesh
from ..tracing import get_tracer
from ..utils import MetricsAggregator
from .sampling import sample
from .tokenizer import load_tokenizer

DEFAULT_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192)


@dataclass
class EngineConfig:
    max_seq_len: int = 2048
    dtype: str = "bfloat16"
    cache_dtype: str = "bfloat16"
    prefill_buckets: tuple = DEFAULT_BUCKETS
    rng_seed: int = 0
    # tokens decoded per jit call (lax.scan on device). Each host<->device
    # sync costs ~100 ms through a tunneled TPU; chunking amortizes it to
    # sync/chunk_len per token. Streaming granularity == chunk_len.
    decode_chunk: int = 16
    # "dense": einsum attention (models/core._attention, XLA-fused);
    # "flash": pallas tiled kernel (ops/flash.py) — no [T,S] score
    # materialization, VMEM-resident online softmax
    attention: str = "dense"


@dataclass
class GenerationResult:
    text: str
    token_ids: list[int]
    prompt_tokens: int
    new_tokens: int
    ttft_s: float  # time to first token
    latency_s: float
    tokens_per_sec: float
    finish_reason: str  # "stop" | "length" | "eos"
    timings: dict = field(default_factory=dict)


class InferenceEngine:
    def __init__(
        self,
        model: str | model_config.ModelConfig,
        params=None,
        mesh=None,
        engine_config: EngineConfig | None = None,
        tokenizer=None,
        checkpoint_path: str | None = None,
    ):
        self.model_cfg = (
            model if isinstance(model, model_config.ModelConfig) else model_config.get_config(model)
        )
        self.engine_cfg = engine_config or EngineConfig()
        # default to the degenerate 1-device mesh; multi-chip serving passes
        # an explicit mesh (the model must divide its axes — validated below)
        self.mesh = mesh if mesh is not None else local_mesh()
        partition.validate_divisibility(self.model_cfg, self.mesh)
        self._validate_attention_impl()
        self.dtype = jnp.dtype(self.engine_cfg.dtype)
        self.max_seq_len = min(self.engine_cfg.max_seq_len, self.model_cfg.max_seq_len)
        self.metrics = MetricsAggregator()

        if params is None and checkpoint_path:
            from ..models.loader import load_checkpoint

            params = load_checkpoint(checkpoint_path, self.model_cfg, dtype=self.dtype)
        if params is None:
            params = core.init_params(
                self.model_cfg, jax.random.key(self.engine_cfg.rng_seed), dtype=self.dtype
            )
        self.params = partition.shard_params(params, self.mesh)
        self.tokenizer = tokenizer or load_tokenizer(checkpoint_path, self.model_cfg.vocab_size)

        self._cache_sharding = NamedSharding(self.mesh, partition.cache_spec())
        self._replicated = NamedSharding(self.mesh, P())
        # one jit object; it specializes per tokens shape (= per bucket)
        self._prefill = jax.jit(self._prefill_fn, donate_argnums=(2,))
        self._decode_compiled: dict[tuple, Callable] = {}
        self._rng = jax.random.key(self.engine_cfg.rng_seed)
        # gateways run execute() on a thread pool: guard the rng stream and
        # the compiled-fn cache (jax itself is safe for concurrent dispatch)
        self._mutex = threading.Lock()

    # ------------------------------------------------------------ compiled fns

    def _attn_fn(self):
        """attn_fn for core.forward per the engine's attention setting."""
        if self.engine_cfg.attention != "flash":
            return None
        from ..ops.flash import flash_attention

        def attn(q, k, v, mask, cfg, positions=None):
            return flash_attention(q, k, v, offset=positions[:, 0])

        return attn

    def _validate_attention_impl(self):
        # pallas_call has no SPMD partitioning rule: under TP the
        # model-sharded KV cache would be all-gathered into the kernel.
        # Same stance as parallel/ring.make_sp_forward's mesh guard.
        if self.engine_cfg.attention == "flash" and (
            self.mesh.shape.get("model", 1) > 1 or self.mesh.shape.get("expert", 1) > 1
        ):
            raise ValueError(
                "attention='flash' requires model=expert=1 in the mesh "
                f"(got {dict(self.mesh.shape)}); use attention='dense' for TP/EP"
            )

    def _prefill_fn(self, params, tokens, cache, true_len):
        """tokens [B, Tb] padded; returns (cache, last_logits [B, V])."""
        logits, cache = core.forward(
            params, self.model_cfg, tokens, cache, jnp.int32(0), attn_fn=self._attn_fn()
        )
        idx = (true_len - 1).reshape(-1, 1, 1)  # [B,1,1]
        last = jnp.take_along_axis(logits, jnp.broadcast_to(idx, (logits.shape[0], 1, logits.shape[2])), axis=1)
        return cache, last[:, 0, :]

    def _decode_chunk_fn(self, temperature, top_k, top_p, params, token, cache, offset, key):
        """Decode `decode_chunk` tokens in one on-device scan.

        token [B]: the current token (to be written at `offset`). Returns
        (tokens [B, K] — the K tokens sampled after `token` — and the cache).
        One host sync per K tokens instead of per token.
        """

        def step(carry, key_t):
            cur, cache, off = carry
            logits, cache = core.forward(
                params, self.model_cfg, cur[:, None], cache, off, attn_fn=self._attn_fn()
            )
            nxt = sample(logits[:, -1, :], key_t, temperature, top_k, top_p)
            return (nxt, cache, off + 1), nxt

        keys = jax.random.split(key, self.engine_cfg.decode_chunk)
        (_, cache, _), toks = jax.lax.scan(step, (token, cache, offset), keys)
        return jnp.moveaxis(toks, 0, 1), cache  # [B, K]

    def _get_decode(self, temperature, top_k, top_p):
        sig = (
            round(float(temperature if temperature is not None else 0.0), 4),
            int(top_k or 0),
            round(float(top_p if top_p is not None else 1.0), 4),
        )
        with self._mutex:
            fn = self._decode_compiled.get(sig)
            if fn is None:
                fn = jax.jit(
                    partial(self._decode_chunk_fn, sig[0], sig[1], sig[2]),
                    donate_argnums=(2,),  # donate the cache for in-place HBM update
                )
                self._decode_compiled[sig] = fn
            return fn

    # ------------------------------------------------------------ helpers

    def _bucket_for(self, n: int) -> int:
        for b in self.engine_cfg.prefill_buckets:
            if b >= n and b <= self.max_seq_len:
                return b
        return self.max_seq_len

    def new_cache(self, batch: int = 1):
        cache = core.init_cache(
            self.model_cfg, batch, self.max_seq_len, jnp.dtype(self.engine_cfg.cache_dtype)
        )
        # fall back axis-by-axis when a cache dim doesn't divide its mesh
        # axis (e.g. batch=1 on a data=2 mesh) instead of crashing device_put
        spec = partition.cache_spec()
        k = cache["k"]
        fitted = P(*[
            e if e is None or k.shape[i] % self.mesh.shape.get(e, 1) == 0 else None
            for i, e in enumerate(spec)
        ])
        return jax.device_put(cache, NamedSharding(self.mesh, fitted))

    def _next_key(self):
        with self._mutex:
            self._rng, sub = jax.random.split(self._rng)
            return sub

    # ------------------------------------------------------------ public API

    def _dispatch(self, prompt, max_new_tokens, temperature, top_k, top_p):
        """Tokenize, prefill, and asynchronously dispatch every decode chunk.

        Chunks chain on-device through (cur, cache); dispatch is ~free, so
        all compute is enqueued before anything is read back. Returns
        (first_token_dev [B], chunk_devs list of [B, K], n_prompt, bucket,
        clamped_max_new_tokens).
        """
        if isinstance(prompt, str):
            ids = self.tokenizer.encode(prompt)
        else:
            ids = list(prompt)
        K = self.engine_cfg.decode_chunk
        # clamp generation to what the cache can hold while keeping at least
        # a small prompt window (callers may pass max_new_tokens == cache
        # size; clamping, not erroring, is the serving behavior)
        min_prompt = max(1, min(len(ids), 16))
        max_gen = self.max_seq_len - 1 - min_prompt
        if max_gen < 1:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} leaves no room in max_seq_len={self.max_seq_len}"
            )
        max_new_tokens = max(0, min(max_new_tokens, max_gen))
        chunks = max(0, -(-(max_new_tokens - 1) // K))  # ceil
        chunks = min(chunks, (max_gen - 1) // K) if K else 0
        max_new_tokens = min(max_new_tokens, 1 + chunks * K)
        gen_capacity = 1 + chunks * K
        budget = self.max_seq_len - gen_capacity - 1
        # left-truncate so prompt + generation fits the cache (the reference
        # simply OOMs/errors here; we keep the most recent context)
        if len(ids) > budget:
            ids = ids[-budget:]
        n = len(ids)
        bucket = self._bucket_for(n)

        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = ids
        cache = self.new_cache(1)
        # dispatch-only (prefill is jit'd + async): wall time here is enqueue
        # + any compile, NOT device time — that shows in device_profile
        with get_tracer().span("engine.prefill_dispatch", prompt_tokens=n, bucket=bucket):
            cache, last_logits = self._prefill(
                self.params, jnp.asarray(tokens), cache, jnp.asarray([n], jnp.int32)
            )
            first = sample(last_logits, self._next_key(), temperature, top_k, top_p)

        # dispatch-only: decode chunks are enqueued async, so this span
        # measures queueing, not device time (that shows in device_profile)
        with get_tracer().span("engine.decode_dispatch", chunks=chunks):
            decode = self._get_decode(temperature, top_k, top_p)
            cur, offset, pending = first, n, []
            for _ in range(chunks):
                toks_dev, cache = decode(
                    self.params, cur, cache, jnp.asarray([offset], jnp.int32), self._next_key()
                )
                cur = toks_dev[:, -1]
                offset += K
                pending.append(toks_dev)
        return first, pending, n, bucket, max_new_tokens

    def _stop_set(self, stop_tokens):
        stop = set(stop_tokens or [])
        eos = self.tokenizer.eos_token_id
        if eos is not None and eos >= 0:
            stop.add(int(eos))
        return stop, eos

    def _result(self, out_ids, n, bucket, finish, t_start, ttft, t_decode0):
        latency = time.perf_counter() - t_start
        decode_time = time.perf_counter() - t_decode0
        tps = len(out_ids) / decode_time if decode_time > 0 and out_ids else 0.0
        self.metrics.record(len(out_ids), latency)
        return GenerationResult(
            text=self.tokenizer.decode(out_ids),
            token_ids=out_ids,
            prompt_tokens=n,
            new_tokens=len(out_ids),
            ttft_s=round(ttft, 4),
            latency_s=round(latency, 4),
            tokens_per_sec=round(tps, 2),
            finish_reason=finish,
            timings={"prefill_bucket": bucket, "decode_s": round(decode_time, 4)},
        )

    def generate_stream(
        self,
        prompt: str | list[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        stop_tokens: list[int] | None = None,
    ) -> Iterator[dict]:
        """Yield {"token": last_id, "tokens": ids, "text": piece} per decode
        chunk, then {"done": True, "result": GenerationResult}. Streaming
        granularity is engine_cfg.decode_chunk tokens (each read through a
        tunneled TPU costs ~100 ms — see _dispatch)."""
        t_start = time.perf_counter()
        first, pending, n, bucket, max_new_tokens = self._dispatch(
            prompt, max_new_tokens, temperature, top_k, top_p
        )
        stop, eos = self._stop_set(stop_tokens)

        tok = int(jax.device_get(first)[0])
        ttft = time.perf_counter() - t_start
        t_decode0 = time.perf_counter()

        out_ids: list[int] = []
        fin: str | None = None
        flushed_text = ""  # cumulative decode → UTF-8-safe incremental text

        def emit(t: int) -> str | None:
            if t in stop:
                return "eos" if t == eos else "stop"
            out_ids.append(t)
            return None

        def text_delta(final: bool = False) -> str:
            # decode the cumulative ids and emit the new suffix; hold back
            # trailing replacement chars (a multi-byte char split across
            # chunks) until the next chunk completes it
            nonlocal flushed_text
            full = self.tokenizer.decode(out_ids)
            if not final:
                full = full.rstrip("�")
            delta = full[len(flushed_text):]
            flushed_text = full
            return delta

        fin = emit(tok) if max_new_tokens > 0 else None
        if fin is None and max_new_tokens > 0:
            yield {"token": tok, "tokens": [tok], "text": text_delta()}
            for toks_dev in pending:
                if fin is not None or len(out_ids) >= max_new_tokens:
                    break
                chunk_toks = [int(t) for t in jax.device_get(toks_dev)[0]]
                emitted = []
                for t in chunk_toks:
                    if len(out_ids) >= max_new_tokens:
                        break
                    fin = emit(t)
                    if fin is not None:
                        break
                    emitted.append(t)
                if emitted:
                    last = len(out_ids) >= max_new_tokens or fin is not None
                    yield {
                        "token": emitted[-1],
                        "tokens": emitted,
                        "text": text_delta(final=last),
                    }
        yield {
            "done": True,
            "result": self._result(
                out_ids, n, bucket, fin or "length", t_start, ttft, t_decode0
            ),
        }

    def generate(self, prompt, **kw) -> GenerationResult:
        """Non-streaming generation: exactly ONE device→host read for the
        whole request (all chunks are concatenated on device first), so
        throughput is compute-bound even over a high-latency TPU tunnel."""
        stop_tokens = kw.pop("stop_tokens", None)
        max_new_tokens = kw.get("max_new_tokens", 128)
        t_start = time.perf_counter()
        first, pending, n, bucket, max_new_tokens = self._dispatch(
            prompt,
            max_new_tokens,
            kw.get("temperature", 0.0),
            kw.get("top_k", 0),
            kw.get("top_p", 1.0),
        )
        stop, eos = self._stop_set(stop_tokens)
        all_dev = jnp.concatenate([first[:, None]] + pending, axis=1) if pending else first[:, None]
        t_decode0 = time.perf_counter()
        toks = [int(t) for t in jax.device_get(all_dev)[0]]
        ttft = time.perf_counter() - t_start  # single read: ttft == full latency

        out_ids, fin = [], None
        for t in toks:
            if len(out_ids) >= max_new_tokens:
                break
            if t in stop:
                fin = "eos" if t == eos else "stop"
                break
            out_ids.append(t)
        return self._result(out_ids, n, bucket, fin or "length", t_start, ttft, t_decode0)

    def score(self, token_ids: list[int]):
        """Per-token logprobs of a sequence (no cache, full forward) — the
        scoring/training-parity path."""
        ids = jnp.asarray([token_ids], jnp.int32)
        logits, _ = core.forward(self.params, self.model_cfg, ids, None, jnp.int32(0))
        logprobs = jax.nn.log_softmax(logits[0, :-1], axis=-1)
        tgt = ids[0, 1:]
        return jax.device_get(jnp.take_along_axis(logprobs, tgt[:, None], axis=1)[:, 0])

    @property
    def info(self) -> dict:
        return {
            "model": self.model_cfg.name,
            "n_params": int(
                sum(np.prod(x.shape) for x in jax.tree.leaves(self.params))
            ),
            "mesh": dict(self.mesh.shape),
            "dtype": str(self.dtype),
            "max_seq_len": self.max_seq_len,
            "platform": jax.devices()[0].platform,
        }
