"""InferenceEngine: the serving core.

TPU-first structure (SURVEY §7 step 2, hard part 1):

- **Bucketed prefill**: prompts pad up to a power-of-two bucket; each bucket
  shape compiles once, bounding the recompile space. Pad K/V written past the
  true length is overwritten by decode exactly when it would enter the
  causal window, so no separate validity mask is needed.
- **Continuous batching** (engine/scheduler.py): concurrent requests share
  ONE paged KV block pool + per-row block tables (engine/paged.py),
  donated through every decode step so XLA updates it in place in HBM;
  rows admit/retire between chunks, a request stops paying compute at
  EOS, per-step cache traffic follows live tokens, and prompt prefixes
  are shared block-level copy-on-write. The old rectangular
  [max_batch, max_seq_len] cache is gone: dense attention serves the
  gathered block view, ``attention="flash"`` runs the ragged
  paged-attention kernel (ops/ragged.py) straight off the pool, and
  ``attention="sp"`` shards the pool's slot dim over the `seq` axis.
- **On-device sampling** inside the jit'd step: one fused
  forward+sample+cache-update program per token; the only host transfer per
  chunk is the sampled token ids (needed for streaming/stop anyway).
- **Mesh-agnostic**: params and cache carry NamedShardings from
  models.partition; the same engine serves a 1-chip node or a v5e-8 TP
  group — jit inserts the collectives.

The generate() contract mirrors what the reference's streaming path provides
(reference hf.py:46-136: max_new_tokens, temperature, stop handling, chunk
callback) minus the transcript parsing, which lives in the service layer.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..metrics import get_registry
from ..models import config as model_config
from ..models import core, partition
from ..parallel.mesh import local_mesh
from ..utils import MetricsAggregator
from .tokenizer import load_tokenizer

logger = logging.getLogger("bee2bee_tpu.engine")

# per-request serving distributions, observed at retirement (scheduler
# thread). TTFT and inter-token (TPOT) are the ROADMAP's "as fast as the
# hardware allows" yardsticks; /metrics exposes their histograms.
_H_TTFT = get_registry().histogram(
    "engine.ttft_ms", "time to first token per request (ms)"
)
_H_INTER_TOKEN = get_registry().histogram(
    "engine.inter_token_ms", "mean inter-token latency per request (ms)"
)
_H_E2E = get_registry().histogram(
    "engine.e2e_latency_ms", "submit-to-done latency per request (ms)"
)
_C_TOKENS_OUT = get_registry().counter(
    "engine.tokens_generated", "tokens generated across all requests"
)

DEFAULT_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192)


def _env_flag(name: str, default: bool) -> bool:
    """Bool knob: unset -> default; "0"/"false"/"off"/"no" -> False."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    return raw.strip().lower() not in ("0", "false", "off", "no")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("%s=%r is not an int; using %d", name, raw, default)
        return default


@dataclass
class EngineConfig:
    max_seq_len: int = 2048
    dtype: str = "bfloat16"
    cache_dtype: str = "bfloat16"
    prefill_buckets: tuple = DEFAULT_BUCKETS
    rng_seed: int = 0
    # tokens decoded per jit call (lax.scan on device). Each host<->device
    # sync costs ~100 ms through a tunneled TPU; chunking amortizes it to
    # sync/chunk_len per token. Streaming granularity == chunk_len, and so
    # is the EOS early-exit granularity (a request stopping mid-chunk pays
    # the rest of that chunk, never the rest of max_new_tokens). 32 measured
    # best on the tunneled v5e chip (16: +1 sync; 64: coarser early exit
    # for no gain).
    decode_chunk: int = 32
    # continuous-batching rows: concurrent requests share one [max_batch]
    # KV cache and decode together (engine/scheduler.py). Decode is
    # HBM-bound on the weights, so extra rows are nearly free throughput.
    max_batch: int = 8
    # readback window: up to this many chunks are dispatched per host sync
    # when no active request is streaming (a sync costs ~75-100 ms through
    # a tunneled TPU — measured; dispatch is ~10 us). The window is also
    # capped by the tightest active row budget, so worst-case post-EOS
    # waste is max_inflight_chunks * decode_chunk tokens, never the rest
    # of max_new_tokens like the round-1 engine.
    max_inflight_chunks: int = 8
    # "dense": einsum attention (models/core._attention) over the
    # gathered block view — covers every score variant incl. ALiBi;
    # "flash": the ragged paged-attention pallas kernel (ops/ragged.py)
    # reading K/V straight from the block pool — no gathered view, no
    # [T,S] score materialization, VMEM-resident online softmax; serves
    # decode, spec-verify and ragged prefill chunks from one kernel and
    # carries sliding windows / logit softcap / the gemma score scale
    # via the dense path's own mask + scalar params;
    # "sp": sequence-parallel serving (parallel/sp_serving.py) — the
    # pool's slot dim is sharded over the mesh's `seq` axis
    # (partition.paged_cache_spec) and attention merges per-shard
    # online-softmax partials via psum over the gathered view; pool HBM
    # and the quadratic prefill term scale 1/seq. Needs seq > 1.
    # "auto": flash when on TPU and the head layout supports the kernel
    # (ops.ragged.validate_ragged_mesh), dense otherwise — resolved once
    # at engine build (interpret-mode pallas off-TPU would be far slower
    # than XLA's fused dense path).
    attention: str = "dense"
    # chunked prefill: process the prompt in fixed chunks of this many
    # tokens instead of one whole-prompt bucket. Bounds dense-attention
    # prefill score memory to [H, chunk, S] (a whole 8k prompt at once is
    # [H, 8k, 8k] — gigabytes), and ONE compiled shape serves every
    # prompt length. None = whole-prompt power-of-two buckets.
    prefill_chunk: int | None = None
    # weight-only quantization: "none" | "int8" (models/quant.py). Decode
    # streams every weight per step, so int8 halves that HBM traffic;
    # activations/KV stay in `dtype`. Applied after checkpoint load,
    # before sharding.
    quantize: str = "none"
    # prompt prefix cache: keep up to this many prompt K/V entries and
    # admit new requests from the longest cached prefix, prefilling only
    # the remainder. Chat transcripts resend the whole history every turn
    # (the reference rebuilds full context per message — its hf.py
    # transcript path), so turn N+1 pays only the delta. Cost depends on
    # the cache layout: rectangular entries each snapshot a full batch-1
    # row cache in HBM; paged entries cost NO extra HBM — they pin the
    # prompt's existing pool blocks (refcounted), and a hit shares those
    # blocks copy-on-write, device-copying at most the final partial
    # block. Pinned blocks are reclaimed LRU-first under pool pressure.
    # 0 = disabled.
    prefix_cache_entries: int = 0
    # DEPRECATED no-op: the paged block pool (engine/paged.py) is now the
    # ONLY cache layout — per-step cache HBM traffic scales with LIVE
    # tokens under every attention impl (the old rectangular cache
    # measured 4x decode cost at bsz=8 with one active row and is
    # deleted). The field is accepted so existing configs/knobs
    # (--paged / BEE2BEE_PAGED) keep parsing.
    paged: bool = True
    # tokens per pool block. Smaller blocks track live length tighter
    # (less over-allocation, finer sharing granularity); larger blocks
    # shrink the table/gather overhead. 16 matches the TPU second-minor
    # tile and means a 64-token prompt costs 4 blocks, not a max_seq row.
    kv_block_size: int = 16
    # total pool blocks (incl. the reserved null block 0). None sizes the
    # pool so exhaustion is impossible: max_batch full rows (plus decode-
    # chunk overshoot) + worst-case pinned prefix entries. Set explicitly
    # to trade HBM for admission backpressure (the scheduler queues, and
    # reclaims prefix pins, when the free list runs dry).
    kv_pool_blocks: int | None = None
    # self-speculative decoding (engine/spec.py): draft up to this many
    # tokens per step by n-gram lookup against the row's own
    # prompt+output, verify them all in ONE [B, K+1] forward, accept the
    # longest exact prefix. Greedy non-penalized rows only (token-for-
    # token parity with plain greedy decode); sampled/penalized rows in
    # the same batch keep the normal decode windows. 0 = off. Composes
    # with attention="dense" AND "flash" — the verify chunk rides the
    # paged write path and the ragged kernel serves the [B, K+1] shape
    # natively; only "sp" lacks the capability (the scheduler detects it
    # off the active attn path and logs once).
    spec_tokens: int = 0
    # suffix n-gram lengths the drafter tries, longest first. A longer
    # match predicts the continuation better; min_match=2 keeps single
    # high-frequency tokens (spaces, newlines) from drafting noise.
    spec_min_match: int = 2
    spec_max_match: int = 8
    # per-row adaptive disable: after spec_probe_tokens drafted tokens,
    # a row whose acceptance rate sits below spec_min_accept stops
    # speculating (the draft lookup + wider verify buy nothing on
    # non-repetitive content).
    spec_min_accept: float = 0.25
    spec_probe_tokens: int = 64
    # model-tier drafter (engine/drafter.py): "" / None = n-gram only;
    # "mesh" = a BEE2BEE_DISAGG=draft peer hosts the model and streams
    # drafts over draft_request/draft_result frames; anything else is a
    # registry name or checkpoint path for a small model loaded RESIDENT
    # beside the target (vocab/tokenizer-compat gated at boot — a
    # mismatch is a typed DrafterLoadError, never a silent garbage-draft
    # loop). Rows where n-gram fails its probe escalate to this tier
    # instead of dropping to plain decode. Requires spec_tokens > 0.
    # None = resolve from BEE2BEE_DRAFTER at construction.
    drafter: str | None = None
    # rng seed for a random-init (registry-name, no checkpoint) drafter.
    # None = the engine's rng_seed — which makes a same-name drafter
    # WEIGHT-IDENTICAL to a random-init target (the bench's CPU proxy
    # for a well-distilled drafter: greedy acceptance ~1).
    drafter_seed: int | None = None
    # batched multi-LoRA serving (adapters/pool.py): slots for hot-
    # swappable adapters over the one resident base model — per-row
    # adapter selection inside the SAME decode step (a mixed batch
    # serves N tenants in one forward; adapter-less batches skip the
    # lora arguments entirely). 0 = off. Adapters page in/out at runtime
    # (engine.load_adapter / the mesh's DHT fetch) without a restart.
    max_adapters: int = 0
    # ---- decode hot-loop mechanisms (docs/PERF.md "Decode hot loop").
    # None = resolve from env at construction so node configs and tests
    # can flip them without plumbing; the resolved value is always a
    # plain bool/int after __post_init__.
    # async dispatch overlap: dispatch window N+1 while window N's token
    # readback is still in flight (BEE2BEE_OVERLAP, default on).
    decode_overlap: bool | None = None
    # depth of the in-flight readback ring. 2 = double-buffered: token
    # emission / stop handling on window W never blocks W+1's dispatch
    # (BEE2BEE_READBACK_DEPTH, default 2; clamped to >= 1).
    readback_depth: int | None = None
    # fused decode root: sampling + penalty-counts application live
    # inside the ONE decode jit root, so a penalized row no longer parks
    # the whole batch on the counts window (BEE2BEE_FUSED_ROOT, default
    # on; off restores the split decode/decode_penalized roots).
    fused_root: bool | None = None
    # persistent-width batches: hold the batch at a sticky width
    # (grow-only; idle-timeout release) instead of riding the pow2
    # resize ladder, with HBM-ledger headroom gating growth
    # (BEE2BEE_BATCH_STICKY, default on).
    batch_sticky: bool | None = None

    def __post_init__(self):
        # <= 0 means "disabled" (NodeConfig uses 0 as its sentinel); a raw
        # 0 reaching the admission loop would make an empty chunk that
        # never advances
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            self.prefill_chunk = None
        if self.kv_block_size < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {self.kv_block_size}")
        if self.spec_tokens < 0:  # NodeConfig's 0-means-disabled sentinel
            self.spec_tokens = 0
        if self.max_adapters < 0:
            self.max_adapters = 0
        if self.spec_tokens and not (
            1 <= self.spec_min_match <= self.spec_max_match
        ):
            raise ValueError(
                f"need 1 <= spec_min_match <= spec_max_match, got "
                f"{self.spec_min_match}..{self.spec_max_match}"
            )
        if self.decode_overlap is None:
            self.decode_overlap = _env_flag("BEE2BEE_OVERLAP", True)
        if self.fused_root is None:
            self.fused_root = _env_flag("BEE2BEE_FUSED_ROOT", True)
        if self.batch_sticky is None:
            self.batch_sticky = _env_flag("BEE2BEE_BATCH_STICKY", True)
        if self.readback_depth is None:
            self.readback_depth = _env_int("BEE2BEE_READBACK_DEPTH", 2)
        self.readback_depth = max(1, int(self.readback_depth))
        if self.drafter is None:
            self.drafter = (os.environ.get("BEE2BEE_DRAFTER") or "").strip()
        if self.drafter_seed is None:
            self.drafter_seed = self.rng_seed
        if self.drafter and not self.spec_tokens:
            raise ValueError(
                "drafter set but spec_tokens is 0: the drafter feeds the "
                "speculative verify path — set spec_tokens (--spec) too"
            )


@dataclass
class GenerationResult:
    text: str
    token_ids: list[int]
    prompt_tokens: int
    new_tokens: int
    ttft_s: float  # time to first token
    latency_s: float
    tokens_per_sec: float
    finish_reason: str  # "stop" | "length" | "eos"
    timings: dict = field(default_factory=dict)


class InferenceEngine:
    def __init__(
        self,
        model: str | model_config.ModelConfig,
        params=None,
        mesh=None,
        engine_config: EngineConfig | None = None,
        tokenizer=None,
        checkpoint_path: str | None = None,
        lora_path: str | None = None,
    ):
        # registry name, 'auto' sentinel, or checkpoint-config fallback —
        # one shared rule (models/config.resolve_model_config; the
        # reference's AutoModel any-checkpoint capability,
        # reference services.py:39-52)
        self.model_cfg = model_config.resolve_model_config(model, checkpoint_path)
        self.engine_cfg = engine_config or EngineConfig()
        # default to the degenerate 1-device mesh; multi-chip serving passes
        # an explicit mesh (the model must divide its axes — validated below)
        self.mesh = mesh if mesh is not None else local_mesh()
        # effective context BEFORE attention validation: _window_binds
        # and the validation error message both read it
        self.max_seq_len = min(self.engine_cfg.max_seq_len, self.model_cfg.max_seq_len)
        partition.validate_divisibility(self.model_cfg, self.mesh)
        if self.engine_cfg.attention == "auto":
            # replace, don't mutate: the caller may share one EngineConfig
            # across engines on different backends/meshes
            self.engine_cfg = dataclasses.replace(
                self.engine_cfg, attention=self._resolve_auto_attention()
            )
        self._validate_attention_impl()
        if self.engine_cfg.quantize not in ("none", "int8", "", None):
            # fail BEFORE the (multi-GB) checkpoint load, like the other
            # config validation above
            raise ValueError(
                f"quantize={self.engine_cfg.quantize!r}: only 'int8' or 'none'"
            )
        self.dtype = jnp.dtype(self.engine_cfg.dtype)
        self.metrics = MetricsAggregator()

        quantized = self.engine_cfg.quantize == "int8"
        if params is None and checkpoint_path:
            from ..models.loader import load_checkpoint

            # quantizing: keep the checkpoint HOST-side so the dense model
            # never materializes in HBM (peak device memory stays int8-sized)
            params = load_checkpoint(
                checkpoint_path, self.model_cfg, dtype=self.dtype, host=quantized
            )
        if params is None:
            params = core.init_params(
                self.model_cfg, jax.random.key(self.engine_cfg.rng_seed), dtype=self.dtype
            )
        if lora_path:
            # base + trained low-rank deltas, merged BEFORE quantization so
            # int8 scales see the finetuned weights (train/lora.py)
            from ..train.lora import load_adapters, merge_lora

            adapters, lcfg = load_adapters(lora_path)
            params = merge_lora(params, adapters, lcfg)
        if quantized:
            from ..models.quant import quantize_params

            # device_get is a no-op for the host-loaded checkpoint path;
            # random-init params (tests/demos) do round-trip, but anything
            # that fit dense at init fits trivially
            params = quantize_params(jax.device_get(params))
        if (
            jax.default_backend() == "cpu"
            and all(n == 1 for n in self.mesh.shape.values())
        ):
            # CPU fallback serving: unstack [L, ...] layers into per-layer
            # contiguous arrays. XLA:CPU can't pre-pack a GEMM operand it
            # must slice out of the stacked array inside the graph — every
            # layer dot drops to a naive kernel (measured 20x per block on
            # distilgpt2 decode). Unrolled layers compile O(L) but CPU
            # compiles fast; TPU keeps the stacked lax.scan (core.forward).
            params = core.unstack_layers(jax.device_get(params))
        self.params = partition.shard_params(params, self.mesh, cfg=self.model_cfg)
        self.tokenizer = tokenizer or load_tokenizer(checkpoint_path, self.model_cfg.vocab_size)

        self._replicated = NamedSharding(self.mesh, P())
        # engine economics plane (engine/introspect.py, ISSUE 15): the
        # retrace sentinel every jit root below registers with, the HBM
        # ledger, and the MFU/goodput meter the scheduler feeds. Built
        # BEFORE the jits so their compiles count from call one.
        from .introspect import EngineIntrospection

        self.introspect = EngineIntrospection(self.model_cfg, self.mesh)
        self.introspect.ledger.register("weights", lambda: self.params)
        # the declared compile space — THE warm-up/bucket-growth contract
        # the sentinel enforces: prefill widths are the configured buckets
        # (clipped to context) + the chunked-prefill width, batch sizes
        # the scheduler's pow2 grow ladder. A shape outside these through
        # a registered root is a steady-state retrace (typed incident).
        prefill_widths = {
            b for b in self.engine_cfg.prefill_buckets if b <= self.max_seq_len
        } | {self.max_seq_len}
        if self.engine_cfg.prefill_chunk:
            prefill_widths.add(self.engine_cfg.prefill_chunk)
        self._declared_prefill_widths = frozenset(prefill_widths)
        # batch buckets: the CLOSURE of {1} under the scheduler's actual
        # resize ops — grow min(2b, max_batch), shrink max(1, b//2) — so
        # a non-pow2 max_batch's shrink ladder (6 -> 3 -> 1) is declared
        # warm-up, not a false storm
        mb = self.engine_cfg.max_batch
        sizes: set[int] = set()
        frontier = {1, mb}
        while frontier:
            b = frontier.pop()
            if b in sizes:
                continue
            sizes.add(b)
            frontier.add(min(2 * b, mb))
            frontier.add(max(1, b // 2))
        self._declared_batch_sizes = frozenset(sizes)
        # one jit object; it specializes per tokens shape (= per bucket)
        self._prefill = self.introspect.sentinel.watch(
            "prefill",
            jax.jit(self._prefill_fn, donate_argnums=(2,)),
            key_fn=self._prefill_key,
            allowed=lambda key: (
                key[0] == 1 and key[1] in self._declared_prefill_widths
            ),
        )
        # speculative-decode verify step: [B, K+1] forward through the
        # same cache write paths, donated like the decode cache
        self._spec_verify = self.introspect.sentinel.watch(
            "spec_verify",
            jax.jit(self._spec_verify_fn, donate_argnums=(4,)),
            key_fn=self._spec_verify_key,
            allowed=lambda key: (
                key[0] in self._declared_batch_sizes
                and key[1] == self.engine_cfg.spec_tokens
            ),
        )
        self._rng = jax.random.key(self.engine_cfg.rng_seed)
        # jitted split: an eager jax.random.split is a blocking round trip
        # on a tunneled chip, and _next_key runs on every admission/window
        self._split_key = jax.jit(lambda k: tuple(jax.random.split(k)))
        # gateways run execute() on a thread pool: guard the rng stream and
        # lazy scheduler creation (jax itself is safe for concurrent dispatch)
        self._mutex = threading.Lock()
        self._scheduler = None  # created on first generate (allocates the
        # shared [max_batch] cache — engines built only for score()/info
        # never pay for it)
        # batched multi-LoRA serving: the hot-swap pool (adapters/pool.py).
        # Construction is cheap — device factors allocate at the first
        # load_adapter, whose rank/targets fix the pool geometry.
        self.adapter_pool = None
        if self.engine_cfg.max_adapters > 0:
            from ..adapters.pool import AdapterPool

            self.adapter_pool = AdapterPool(
                self.model_cfg, self.engine_cfg.max_adapters
            )
            # HBM ledger: the stacked A/B factors + scales are the
            # "adapter pool vs KV pool" squeeze the ledger exists to
            # show ((None, None) before the first load reads as 0)
            self.introspect.ledger.register(
                "adapter_pool", lambda: self.adapter_pool.device_args()
            )
        # model-tier drafter (engine/drafter.py): loaded RESIDENT beside
        # the target, tokenizer-compat gated (typed DrafterLoadError at
        # boot — never a silent garbage-draft loop at serve time).
        # "mesh" loads nothing here: the scheduler builds the MeshDrafter
        # client and meshnet/draft.py attaches the transport.
        self.drafter_model = None
        if self.engine_cfg.drafter and self.engine_cfg.drafter != "mesh":
            from .drafter import DraftModel, validate_drafter_compat

            spec = self.engine_cfg.drafter
            ckpt = spec if os.path.exists(spec) else None
            self.drafter_model = DraftModel(
                "auto" if ckpt else spec,
                spec_tokens=self.engine_cfg.spec_tokens,
                batch=self.engine_cfg.max_batch,
                target_max_seq_len=self.max_seq_len,
                dtype=self.dtype,
                seed=self.engine_cfg.drafter_seed,
                checkpoint_path=ckpt,
                sentinel=self.introspect.sentinel,
            )
            validate_drafter_compat(
                self.model_cfg, self.tokenizer, self.drafter_model.cfg,
                self.drafter_model.tokenizer or self.tokenizer,
            )
            self.introspect.ledger.register(
                "drafter", lambda: self.drafter_model.hbm_source()
                if self.drafter_model is not None else None
            )

    # ------------------------------------------------------------ compiled fns

    @staticmethod
    def _prefill_key(params, tokens, cache, true_len, offset,
                     block_tables=None, write_floor=None, write_ceil=None,
                     adapters=None, aids=None, ascales=None):
        """Sentinel shape key for the prefill root: the dims that select
        a compiled variant — batch rows, the padded token width (the
        bucket), the block-table width bucket, and the None-flags of the
        optional operands (each flag is a distinct legitimate trace)."""
        return (
            int(tokens.shape[0]), int(tokens.shape[1]),
            None if block_tables is None else int(block_tables.shape[1]),
            write_floor is not None, write_ceil is not None,
            adapters is not None,
        )

    @staticmethod
    def _spec_verify_key(params, cur, drafts, draft_lens, cache, offsets,
                         temps, topks, topps, minps=None, key=None,
                         tables=None, adapters=None, aids=None, ascales=None,
                         counts=None, reps=None, press=None, freqs=None):
        """Sentinel shape key for the spec-verify root: batch bucket,
        draft width K, and the optional-operand flags (counts rides along
        when the batch holds penalized rows — the fused-root discipline,
        docs/PERF.md "Decode hot loop")."""
        return (
            int(cur.shape[0]), int(drafts.shape[1]),
            minps is not None,
            None if tables is None else int(tables.shape[1]),
            adapters is not None,
            counts is not None,
        )

    def _attn_fn(self):
        """attn_fn for core.forward per the engine's attention setting.
        "flash" is the ragged paged kernel (ops/ragged.py) — it reads the
        block pool directly (core.forward detects the `ragged` marker and
        skips the gathered-view build). Under a non-trivial mesh the
        pallas kernel runs per-shard via shard_map — pallas_call has no
        SPMD partitioning rule, so sharding propagation would all-gather
        it."""
        if self.engine_cfg.attention == "flash":
            from ..ops.ragged import make_ragged_attn_fn

            return make_ragged_attn_fn(self.mesh)
        if self.engine_cfg.attention == "sp":
            from ..parallel.sp_serving import make_sp_attn_fn

            return make_sp_attn_fn(self.mesh)
        return None

    def _resolve_auto_attention(self) -> str:
        """attention='auto' → 'flash' (the ragged paged kernel) when THIS
        engine's mesh devices are TPU and the head layout supports it,
        'sp' on a seq-sharded mesh, else 'dense'. Measured rationale
        (docs/PERF.md r4): flash's whole-graph compile is ~2x faster than
        dense's, and the ragged kernel never materializes the gathered
        block view or [T, S] scores. On non-TPU devices the kernel runs
        in pallas interpret mode — orders of magnitude slower than XLA's
        fused dense einsum — so those resolve to dense. The platform
        comes from the mesh, not jax.devices(): an explicit CPU mesh on
        a TPU-default host must not pick flash. Sliding windows and the
        gemma-2 score math ride the ragged kernel (mask + scalar params);
        only ALiBi stays dense-only."""
        from ..ops.ragged import validate_ragged_mesh

        if self.mesh.shape.get("seq", 1) > 1:
            # a seq axis exists for exactly one reason: sequence-parallel
            # pool sharding. flash/dense would leave the pool replicated
            # across the seq group (paged_cache_spec seq-shards only
            # under "sp") — silent 1/seq HBM-scaling loss
            if self.model_cfg.pos_embedding == "alibi":
                raise ValueError(
                    "no attention impl supports ALiBi on a seq-sharded "
                    "mesh; drop the seq axis"
                )
            if self._gemma2_score_math():
                raise ValueError(
                    "no attention impl supports gemma-2 score math "
                    "(softcap / attn_scale / alternating windows) on a "
                    "seq-sharded mesh; drop the seq axis"
                )
            if self._window_binds():
                raise ValueError(
                    f"no attention impl supports sliding_window="
                    f"{self.model_cfg.sliding_window} on a seq-sharded mesh; "
                    "drop the seq axis or serve full-causal"
                )
            logger.info("attention=auto -> sp (mesh has a seq axis)")
            return "sp"
        if self.model_cfg.pos_embedding == "alibi":
            logger.info("attention=auto -> dense (ALiBi bias: only the "
                        "dense path implements it)")
            return "dense"
        if self.mesh.devices.flat[0].platform != "tpu":
            logger.info("attention=auto -> dense (mesh devices are not TPU)")
            return "dense"
        try:
            validate_ragged_mesh(self.model_cfg, self.mesh)
        except ValueError as e:  # unsupported head layout
            logger.info("attention=auto -> dense (%s)", e)
            return "dense"
        logger.info("attention=auto -> flash (ragged paged kernel)")
        return "flash"

    def _gemma2_score_math(self) -> bool:
        """True when the model needs score math only the dense path
        implements: attention-logit softcap, a non-head_dim score scale,
        or per-layer window alternation (gemma-2)."""
        cfg = self.model_cfg
        return bool(
            cfg.attn_logit_softcap
            or (cfg.attn_scale and cfg.attn_scale != cfg.head_dim)
            or (cfg.sliding_window and cfg.sliding_window_every > 1)
        )

    def _window_binds(self) -> bool:
        """True iff the model's sliding window can actually mask a cache
        position at THIS engine's context length. zephyr/mistral ship
        window == max context (4096): with cache capacity <= 4096 the
        window clause is always true and full-causal kernels are exact —
        rejecting flash/sp there would be a pure perf regression."""
        w = self.model_cfg.sliding_window
        return bool(w) and w < self.max_seq_len

    def _validate_attention_impl(self):
        if (self.engine_cfg.attention in ("flash", "sp")
                and self.model_cfg.pos_embedding == "alibi"):
            raise ValueError(
                f"attention={self.engine_cfg.attention!r} does not implement "
                f"the ALiBi score bias ({self.model_cfg.name!r}); use "
                "attention='dense' (the kernels would silently drop the "
                "per-head position bias)"
            )
        if self.engine_cfg.attention == "sp" and self._gemma2_score_math():
            # the RAGGED kernel (flash) carries softcap/attn_scale as
            # scalar params and the window alternation via the dense
            # path's mask; sp's partial-merge math hardcodes 1/sqrt(hd)
            raise ValueError(
                f"attention='sp' does not implement gemma-2's score math "
                f"({self.model_cfg.name!r}: attention softcap / "
                "query_pre_attn_scalar / alternating windows); use "
                "attention='dense' or 'flash' — the sp partials hardcode "
                "1/sqrt(hd) and no tanh cap, so logits would silently "
                "diverge"
            )
        if self.engine_cfg.attention == "sp" and self._window_binds():
            raise ValueError(
                f"attention='sp' does not implement sliding_window="
                f"{self.model_cfg.sliding_window} at context "
                f"{self.max_seq_len} ({self.model_cfg.name!r}); use "
                "attention='dense' or 'flash' (sp would silently attend "
                "beyond the window)"
            )
        if (self.engine_cfg.attention in ("dense", "flash")
                and self.mesh.shape.get("seq", 1) > 1):
            # a seq axis shards the pool's slot dim only under 'sp';
            # dense/flash would silently serve a pool REPLICATED across
            # the whole seq group — the exact 1/seq HBM loss the axis
            # exists to avoid (the pre-round-8 paged guard, re-anchored)
            raise ValueError(
                f"attention={self.engine_cfg.attention!r} does not shard "
                "the paged pool over a seq axis; use attention='sp' or "
                "drop the seq axis"
            )
        if self.engine_cfg.attention == "flash":
            from ..ops.ragged import validate_ragged_mesh

            validate_ragged_mesh(self.model_cfg, self.mesh)
        elif self.engine_cfg.attention == "sp":
            from ..parallel.sp_serving import validate_sp_mesh

            validate_sp_mesh(self.model_cfg, self.engine_cfg, self.mesh)

    def _prefill_fn(self, params, tokens, cache, true_len, offset,
                    block_tables=None, write_floor=None, write_ceil=None,
                    adapters=None, aids=None, ascales=None):
        """tokens [B, Tb] padded; returns (cache, last_logits [B, V]).
        `offset` is the global cache position of tokens[:, 0] — 0 for a
        whole-prompt prefill, the running position for chunked prefill.
        `true_len` is the valid length WITHIN this chunk. With
        `block_tables`, `cache` is the paged pool and the chunk scatters
        into the row's mapped blocks (core.forward's paged path);
        `write_floor` keeps re-fed positions below a CoW share point from
        rewriting shared donor blocks, `write_ceil` drops the padded tail
        so short prompts only claim blocks covering their real length.
        `adapters`/`aids`/`ascales` (adapters/pool.py): the row's LoRA
        factors apply to the PROMPT too — an adapted wk/wv writes
        adapter-specific K/V, which is exactly why adapter rows never
        share the base model's prefix cache (scheduler guard)."""
        logits, cache = core.forward(
            params, self.model_cfg, tokens, cache, offset,
            attn_fn=self._attn_fn(), block_tables=block_tables,
            paged_write_floor=write_floor, paged_write_ceil=write_ceil,
            adapters=adapters, adapter_ids=aids, adapter_scales=ascales,
        )
        idx = (true_len - 1).reshape(-1, 1, 1)  # [B,1,1]
        last = jnp.take_along_axis(logits, jnp.broadcast_to(idx, (logits.shape[0], 1, logits.shape[2])), axis=1)
        return cache, last[:, 0, :]

    def _spec_verify_fn(self, params, cur, drafts, draft_lens, cache, offsets,
                        temps, topks, topps, minps, key, tables=None,
                        adapters=None, aids=None, ascales=None,
                        counts=None, reps=None, press=None, freqs=None):
        """Speculative-decode verify: one [B, K+1] forward checks a whole
        draft. Returns (next_tok [B], cache, accepted [B]) — plus the
        updated ``counts`` when penalty bookkeeping rides along.

        ``cur`` [B] is each row's last accepted token, ``drafts`` [B, K]
        the proposed continuations (padded with zeros past
        ``draft_lens`` [B]). The chunk [cur | drafts] runs through the
        SAME cache write path as decode (rectangular vmapped
        dynamic-update or paged block scatter via ``tables``) at each
        row's offset. Position j's logits predict token j+1, so a draft
        token is correct iff it equals the greedy argmax one position
        earlier; ``accepted`` is the longest such prefix (capped at
        draft_lens — pad positions never count). The returned token is
        sampled from the logits AT the accept position: for greedy rows
        that is exactly the argmax plain decode would have produced
        (token-for-token parity), for non-drafting sampled rows
        (draft_lens == 0) it is their normal one-token sample from
        position 0. Rejected positions hold stale K/V but sit at/past
        the row's new offset (offset + accepted + 1), where the causal
        invariant masks or overwrites them — rollback costs nothing.
        """
        from .sampling import sample_batched

        B, K = drafts.shape
        tokens = jnp.concatenate([cur[:, None], drafts], axis=1)  # [B, K+1]
        logits, cache = core.forward(
            params, self.model_cfg, tokens, cache, offsets,
            attn_fn=self._attn_fn(), block_tables=tables,
            adapters=adapters, adapter_ids=aids, adapter_scales=ascales,
        )
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
        pos = jnp.arange(K, dtype=jnp.int32)[None, :]
        match = (drafts == greedy[:, :-1]) & (pos < draft_lens[:, None])
        # longest all-match prefix: cumprod zeroes everything after the
        # first mismatch, the sum counts the survivors
        accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        idx = accepted.reshape(-1, 1, 1)  # [B,1,1]
        last = jnp.take_along_axis(
            logits, jnp.broadcast_to(idx, (B, 1, logits.shape[2])), axis=1
        )[:, 0, :]
        if counts is None:
            nxt = sample_batched(last, key, temps, topks, topps, minps)
            return nxt.astype(jnp.int32), cache, accepted
        # fused penalty bookkeeping (docs/PERF.md "Decode hot loop"): a
        # penalized row never drafts (scheduler._spec_eligible), so its
        # accepted is 0 and the draft bump below is a masked no-op for it;
        # non-drafting rows still need their ACCEPTED drafts counted so
        # the shared [B,2,V] gen-counts stay coherent across the batch.
        gain = (pos < accepted[:, None]).astype(counts.dtype)  # [B, K]
        counts = counts.at[jnp.arange(B)[:, None], 1, drafts].add(gain)
        nxt = sample_batched(last, key, temps, topks, topps, minps,
                             counts, reps, press, freqs)
        nxt = nxt.astype(jnp.int32)
        counts = counts.at[jnp.arange(B), 1, nxt].add(1)
        return nxt, cache, accepted, counts

    # ------------------------------------------------------------ helpers

    def _bucket_for(self, n: int) -> int:
        for b in self.engine_cfg.prefill_buckets:
            if b >= n and b <= self.max_seq_len:
                return b
        return self.max_seq_len

    def _fit_spec(self, spec: P, shape: tuple[int, ...]) -> P:
        """Fall back axis-by-axis when a dim doesn't divide its mesh axis
        (e.g. batch=1 on a data=2 mesh) instead of crashing device_put."""
        return P(*[
            e if e is None or shape[i] % self.mesh.shape.get(e, 1) == 0 else None
            for i, e in enumerate(spec)
        ])

    # ---- paged-pool geometry (engine/paged.py holds the allocator) ----

    @property
    def blocks_per_row(self) -> int:
        """Max pool blocks one row can map: capacity plus the decode-chunk
        overshoot (a readback window may write up to decode_chunk - 2
        positions past capacity before the host sees the stop; the row
        owns real blocks for that overshoot — an out-of-table position
        would otherwise depend on jax's OOB gather/scatter defaults
        instead of landing in a block the row owns)."""
        from .paged import ceil_div

        return ceil_div(
            self.max_seq_len + self.engine_cfg.decode_chunk,
            self.engine_cfg.kv_block_size,
        )

    @property
    def pool_blocks(self) -> int:
        """Total pool blocks: explicit kv_pool_blocks, or sized so the
        free list cannot run dry (null block + max_batch full rows +
        worst-case pinned prefix entries)."""
        from .paged import ceil_div

        if self.engine_cfg.kv_pool_blocks is not None:
            return self.engine_cfg.kv_pool_blocks
        pin = ceil_div(self.max_seq_len, self.engine_cfg.kv_block_size)
        return (
            1
            + self.engine_cfg.max_batch * self.blocks_per_row
            + self.engine_cfg.prefix_cache_entries * pin
        )

    @property
    def kv_info(self) -> dict:
        """KV-pool identity (ISSUE 12 drive-by): which cache layout this
        engine runs and its effective capacity — rides engine.info AND
        the telemetry digest, so /mesh/health and the router see which
        peers serve the doubled int8 pool, not just a raw block count
        whose bytes-per-block they can't know. Pure config arithmetic —
        never allocates the pool or the scheduler."""
        return {
            "cache_dtype": str(jnp.dtype(self.engine_cfg.cache_dtype)),
            "block_size": int(self.engine_cfg.kv_block_size),
            "pool_blocks": int(self.pool_blocks),
            # usable tokens (block 0 is the reserved null block)
            "capacity_tokens": int(
                (self.pool_blocks - 1) * self.engine_cfg.kv_block_size
            ),
        }

    @property
    def kv_quantized(self) -> bool:
        """True when the pool stores int8 pages + per-page-per-head
        scales (EngineConfig.cache_dtype='int8' / --kv-quant)."""
        return jnp.dtype(self.engine_cfg.cache_dtype) == jnp.int8

    def new_pool(self):
        """The paged KV block pool, placed with the kv-head `model` spec
        (partition.paged_cache_spec) so TP serving gathers stay local;
        under attention='sp' the slot dim additionally shards over `seq`
        (per-device pool memory 1/seq — the long-context scaling). An
        int8 pool (cache_dtype='int8') carries k_scale/v_scale arrays,
        sharded like the pool's kv-head dim (partition.paged_scale_spec)."""
        pool = core.init_paged_pool(
            self.model_cfg, self.pool_blocks, self.engine_cfg.kv_block_size,
            jnp.dtype(self.engine_cfg.cache_dtype),
        )
        spec = partition.paged_cache_spec(
            self.model_cfg, self.mesh,
            seq_sharded=self.engine_cfg.attention == "sp",
        )
        sspec = partition.paged_scale_spec(self.model_cfg, self.mesh)
        shardings = {
            name: NamedSharding(
                self.mesh,
                self._fit_spec(spec if arr.ndim == 5 else sspec, arr.shape),
            )
            for name, arr in pool.items()
        }
        return jax.device_put(pool, shardings)

    def _next_key(self):
        with self._mutex:
            self._rng, sub = self._split_key(self._rng)
            return sub

    # ---------------------------------------------- multi-adapter serving

    def load_adapter(self, name: str, adapters: dict | None = None,
                     lcfg=None, path: str | None = None) -> int:
        """Pin one LoRA adapter into the hot-swap pool (fresh load,
        in-place refresh, or LRU-evicting a cold adapter) WITHOUT
        restarting the engine — in-flight generations keep the factors
        they were dispatched with. Pass (adapters, lcfg) directly (the
        DHT fetch path) or ``path`` to an adapter .npz, whose versioned
        sha256 manifest is verified on read. Typed AdapterLoadError on a
        corrupt/mismatched adapter; returns the pool slot."""
        if self.adapter_pool is None:
            raise RuntimeError(
                "multi-adapter serving is off (EngineConfig.max_adapters=0)"
            )
        if path is not None:
            from ..train.lora import load_adapters

            adapters, lcfg = load_adapters(path, model_cfg=self.model_cfg)
        if adapters is None or lcfg is None:
            raise ValueError("load_adapter needs (adapters, lcfg) or path")
        return self.adapter_pool.load(name, adapters, lcfg)

    def unload_adapter(self, name: str) -> bool:
        """Evict a resident adapter; AdapterPoolBusy while rows are in
        flight on it (the refcount hot-swap guard)."""
        if self.adapter_pool is None:
            return False
        return self.adapter_pool.evict(name)

    def has_adapter(self, name: str) -> bool:
        return self.adapter_pool is not None and self.adapter_pool.has(name)

    def resident_adapters(self) -> list[str]:
        return self.adapter_pool.resident() if self.adapter_pool else []

    # ------------------------------------------------------------ public API

    @property
    def scheduler(self):
        """The continuous-batching scheduler (lazy: allocates the shared
        [max_batch] KV cache on first use)."""
        if self._scheduler is None:
            from .scheduler import BatchScheduler

            with self._mutex:
                if self._scheduler is None:
                    self._scheduler = BatchScheduler(
                        self, max_batch=self.engine_cfg.max_batch
                    )
        return self._scheduler

    def close(self):
        """Stop the scheduler thread (idempotent). The swap happens under
        _mutex (so a concurrent lazy creation can't be missed) but
        shutdown() runs outside it — the scheduler thread takes _mutex in
        _next_key, so joining while holding it would stall."""
        with self._mutex:
            sch, self._scheduler = self._scheduler, None
        if sch is not None:
            sch.shutdown()
        if self.drafter_model is not None:
            self.drafter_model.close()
            self.drafter_model = None
        # drop out of the economics digest (a closed engine must not keep
        # its params pinned through the ledger, nor report stale gauges)
        self.introspect.close()

    @staticmethod
    def _event_error(ev: dict) -> Exception:
        """Typed exception for a failed-generation event: an admission-
        race unknown_adapter keeps its type across the event queue (the
        serving surfaces map it to 404 / a typed gen_error) — everything
        else stays the generic RuntimeError."""
        if ev.get("error_kind") == "unknown_adapter":
            from ..adapters.pool import UnknownAdapter

            return UnknownAdapter(ev.get("error", "unknown adapter"))
        return RuntimeError(ev.get("error", "generation failed"))

    def _stop_set(self, stop_tokens):
        stop = set(int(t) for t in (stop_tokens or []))
        eos = self.tokenizer.eos_token_id
        if eos is not None and eos >= 0:
            stop.add(int(eos))
        return stop, eos

    def _make_request(
        self, prompt, max_new_tokens, temperature, top_k, top_p, stop_tokens,
        stream: bool = False, repetition_penalty: float = 1.0,
        presence_penalty: float = 0.0, frequency_penalty: float = 0.0,
        min_p: float = 0.0, tenant: str = "default",
        adapter: str | None = None,
    ):
        from .scheduler import Request

        ids = self.tokenizer.encode(prompt) if isinstance(prompt, str) else list(prompt)
        # clamp generation to what the cache can hold while keeping at least
        # a small prompt window (callers may pass max_new_tokens == cache
        # size; clamping, not erroring, is the serving behavior)
        min_prompt = max(1, min(len(ids), 16))
        max_gen = self.max_seq_len - 1 - min_prompt
        if max_gen < 1:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} leaves no room in "
                f"max_seq_len={self.max_seq_len}"
            )
        max_new_tokens = max(0, min(max_new_tokens, max_gen))
        # left-truncate so prompt + generation fits the cache (the reference
        # simply OOMs/errors here; we keep the most recent context)
        budget = self.max_seq_len - 1 - max(max_new_tokens, 1)
        if len(ids) > budget:
            ids = ids[-budget:]
        if repetition_penalty is not None and repetition_penalty <= 0:
            raise ValueError(
                f"repetition_penalty must be > 0, got {repetition_penalty}"
            )
        if min_p is not None and not (0.0 <= min_p <= 1.0):
            # min_p > 1 would mask EVERY token (floor above the max prob)
            # and degenerate to token 0 — reject, don't silently garble
            raise ValueError(f"min_p must be in [0, 1], got {min_p}")
        if adapter:
            # typed BEFORE submission (UnknownAdapter → /v1 404, p2p
            # unknown_adapter): serving is off, or the adapter is not
            # resident and nothing upstream (node.ensure_adapter) paged
            # it in. The admission-time acquire still re-checks — an
            # eviction can race a queued request.
            from ..adapters.pool import UnknownAdapter

            if self.adapter_pool is None:
                raise UnknownAdapter(
                    f"adapter {adapter!r}: multi-adapter serving is off "
                    "(EngineConfig.max_adapters=0)"
                )
            if not self.adapter_pool.has(adapter):
                raise UnknownAdapter(f"adapter {adapter!r} is not resident")
        stop, eos = self._stop_set(stop_tokens)
        return Request(
            ids, max_new_tokens, temperature, top_k, top_p, stop, eos,
            self.tokenizer, stream=stream,
            repetition_penalty=repetition_penalty,
            presence_penalty=presence_penalty,
            frequency_penalty=frequency_penalty,
            min_p=min_p,
            tenant=tenant,
            adapter=adapter,
        )

    def _build_result(self, req) -> GenerationResult:
        t = req.timing
        t_first = t.t_first or t.t_done
        latency = t.t_done - t.t_submit
        decode_time = t.t_done - t_first
        n_out = len(req.out_ids)
        tps = n_out / decode_time if decode_time > 0 and n_out else 0.0
        self.metrics.record(n_out, latency)
        ttft_ms = (t_first - t.t_submit) * 1000.0
        if n_out or req.finish != "cancelled":
            # a request cancelled while still queued never produced a
            # token: its "ttft" would be the client's abandon wait, which
            # would skew the serving distributions under cancel bursts
            _H_TTFT.observe(ttft_ms)
            _H_E2E.observe(latency * 1000.0)
            if n_out > 1:
                _H_INTER_TOKEN.observe(decode_time * 1000.0 / (n_out - 1))
            _C_TOKENS_OUT.inc(n_out)
        # the client-facing latency breakdown (ISSUE 5): rides the result
        # through the service layer onto gen_success frames, so the caller
        # sees WHERE its latency went without scraping any node.
        # prefill_ms includes the first-token sample+readback (the device
        # sync that makes the token observable — the client-visible cost).
        # t_admit == 0 marks requests that never entered admission
        # (cancelled in queue / zero budget): no queue/prefill split exists.
        timings = {
            "prefill_bucket": req.bucket,
            "decode_s": round(decode_time, 4),
            "chunks": req.chunks_decoded,
            "queue_wait_ms": (
                round((t.t_admit - t.t_submit) * 1000.0, 3) if t.t_admit else None
            ),
            "prefill_ms": (
                round((t_first - t.t_admit) * 1000.0, 3) if t.t_admit else None
            ),
            "ttft_ms": round(ttft_ms, 3),
            "decode_tokens": n_out,
            "tokens_per_s": round(tps, 2),
            "spec_acceptance": (
                round(req.spec_accepted / req.spec_drafted, 4)
                if req.spec_drafted else None
            ),
        }
        return GenerationResult(
            text=self.tokenizer.decode(req.out_ids),
            token_ids=list(req.out_ids),
            prompt_tokens=req.prompt_tokens,
            new_tokens=n_out,
            ttft_s=round(t_first - t.t_submit, 4),
            latency_s=round(latency, 4),
            tokens_per_sec=round(tps, 2),
            finish_reason=req.finish or "length",
            timings=timings,
        )

    def generate_stream(
        self,
        prompt: str | list[int],
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        stop_tokens: list[int] | None = None,
        repetition_penalty: float = 1.0,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        min_p: float = 0.0,
        tenant: str = "default",
        adapter: str | None = None,
    ) -> Iterator[dict]:
        """Yield {"token": last_id, "tokens": ids, "text": piece} per decode
        chunk, then {"done": True, "result": GenerationResult}. Streaming
        granularity is engine_cfg.decode_chunk tokens. Requests from
        concurrent callers share the scheduler's batch — submission order
        is admission order; rows decode together (including rows on
        DIFFERENT adapters: per-row selection inside one decode step)."""
        req = self._make_request(
            prompt, max_new_tokens, temperature, top_k, top_p, stop_tokens,
            stream=True, repetition_penalty=repetition_penalty,
            presence_penalty=presence_penalty,
            frequency_penalty=frequency_penalty,
            min_p=min_p,
            tenant=tenant,
            adapter=adapter,
        )
        if req.max_new_tokens <= 0:
            req.timing.t_first = req.timing.t_done = time.perf_counter()
            yield {"done": True, "result": self._build_result(req)}
            return
        self.scheduler.submit(req)
        try:
            while True:
                ev = req.events.get()
                if ev.get("done") and ev.get("result") is None:
                    raise self._event_error(ev)
                yield ev
                if ev.get("done"):
                    return
        finally:
            # consumer closed the generator early (e.g. a stop marker
            # completed in the service layer): release the batch row
            # instead of decoding to the token budget for nobody
            if req.finish is None:
                req.cancelled = True

    def generate(self, prompt, **kw) -> GenerationResult:
        """Non-streaming generation via the same scheduler path; blocks
        until the request retires (EOS / stop / budget)."""
        stop_tokens = kw.pop("stop_tokens", None)
        req = self._make_request(
            prompt,
            kw.get("max_new_tokens", 128),
            kw.get("temperature", 0.0),
            kw.get("top_k", 0),
            kw.get("top_p", 1.0),
            stop_tokens,
            repetition_penalty=kw.get("repetition_penalty", 1.0),
            presence_penalty=kw.get("presence_penalty", 0.0),
            frequency_penalty=kw.get("frequency_penalty", 0.0),
            min_p=kw.get("min_p", 0.0),
            tenant=kw.get("tenant", "default"),
            adapter=kw.get("adapter"),
        )
        if req.max_new_tokens <= 0:
            req.timing.t_first = req.timing.t_done = time.perf_counter()
            return self._build_result(req)
        self.scheduler.submit(req)
        while True:
            ev = req.events.get()
            if ev.get("done"):
                if ev.get("result") is None:
                    raise self._event_error(ev)
                return ev["result"]

    # ---------------------------------------------------- live migration

    def migration_signature(self) -> dict:
        """Pool-compat fingerprint a KV import is validated against: two
        engines whose signatures match have bit-compatible pool block
        layouts (same per-layer K/V geometry, block size and storage
        dtype), so exported blocks scatter straight in."""
        cfg = self.model_cfg
        return {
            "model": cfg.name,
            "n_layers": cfg.n_layers,
            "n_kv_heads": cfg.n_kv_heads,
            "head_dim": cfg.head_dim,
            "block_size": self.engine_cfg.kv_block_size,
            "cache_dtype": str(jnp.dtype(self.engine_cfg.cache_dtype)),
        }

    def import_generation(self, snap: dict, kv: dict | None = None):
        """Resume a migrated generation (scheduler.checkpoint's snapshot):
        rebuild the Request, prime its accepted output, and submit it on
        the import path — with ``kv`` (host {"k","v"} block arrays) the
        scheduler scatters the shipped blocks and decodes on with ZERO
        prefill; without, it re-prefills prompt + accepted (the fallback
        rung). Returns the live Request; its events queue carries
        {"imported": True} on success, then the usual token/done events.
        Raises ValueError on a snapshot this engine cannot host."""
        from .scheduler import Request

        ids = [int(t) for t in snap.get("ids") or []]
        out = [int(t) for t in snap.get("out") or []]
        if not ids:
            raise ValueError("import: empty prompt")
        if snap.get("model") and snap["model"] != self.model_cfg.name:
            raise ValueError(
                f"import: snapshot is for model {snap['model']!r}, "
                f"this engine serves {self.model_cfg.name!r}"
            )
        adapter = snap.get("adapter") or None
        if adapter and not self.has_adapter(adapter):
            # the row's KV was computed (and its decode continues) under
            # THIS adapter's wk/wv deltas — resuming without it would be
            # silent corruption, and the re-prefill rung would recompute
            # the wrong K/V too. Typed refusal; the exporter's ladder
            # tries another target (migrate.py types this 'incompatible').
            raise ValueError(
                f"import: adapter {adapter!r} is not resident on this engine"
            )
        req = Request(
            ids,
            int(snap.get("max_new_tokens") or 0),
            snap.get("temperature", 0.0),
            int(snap.get("top_k") or 0),
            float(snap.get("top_p") if snap.get("top_p") is not None else 1.0),
            set(int(t) for t in snap.get("stop") or []),
            None if snap.get("eos") is None else int(snap["eos"]),
            self.tokenizer,
            stream=True,  # the migration bridge reads token events
            repetition_penalty=float(snap.get("repetition_penalty") or 1.0),
            presence_penalty=float(snap.get("presence_penalty") or 0.0),
            frequency_penalty=float(snap.get("frequency_penalty") or 0.0),
            min_p=float(snap.get("min_p") or 0.0),
            tenant=str(snap.get("tenant") or "default"),
            adapter=adapter,
        )
        req.out_ids = out
        # the already-streamed text was emitted at the SOURCE; the local
        # delta decoder must start past it or the first resumed chunk
        # would replay the whole output
        req._flushed_text = self.tokenizer.decode(out) if out else ""
        if kv is not None:
            if not out:
                raise ValueError("import: KV snapshot without accepted tokens")
            offset = int(snap.get("offset") or 0)
            if offset != len(ids) + len(out) - 1:
                raise ValueError(
                    f"import: offset {offset} breaks the live-row invariant "
                    f"(prompt {len(ids)} + out {len(out)} - 1)"
                )
            if offset + 1 >= self.max_seq_len:
                raise ValueError(
                    f"import: offset {offset} leaves no room in "
                    f"max_seq_len={self.max_seq_len}"
                )
            if int(snap.get("block_size") or 0) != self.engine_cfg.kv_block_size:
                raise ValueError(
                    f"import: block_size {snap.get('block_size')} != "
                    f"{self.engine_cfg.kv_block_size}"
                )
            # the block arrays must match the pool geometry EXACTLY —
            # a malformed/mismatched export must reject typed here, not
            # raise on the scheduler thread (whose catch-all would fail
            # every in-flight request on this node). An int8 pool demands
            # the scale tensors too (and ONLY then): dequantizing shipped
            # pages with absent/mismatched scales is silent corruption.
            from .paged import ceil_div

            cfg = self.model_cfg
            nb = ceil_div(offset, self.engine_cfg.kv_block_size)
            cache_dt = jnp.dtype(self.engine_cfg.cache_dtype)
            pool_shape = (
                cfg.n_layers, cfg.n_kv_heads, nb,
                self.engine_cfg.kv_block_size, cfg.head_dim,
            )
            want = {"k": (pool_shape, cache_dt), "v": (pool_shape, cache_dt)}
            if self.kv_quantized:
                sshape = (cfg.n_layers, cfg.n_kv_heads, nb)
                want["k_scale"] = (sshape, jnp.dtype(jnp.float32))
                want["v_scale"] = (sshape, jnp.dtype(jnp.float32))
            got_names = set(kv) if isinstance(kv, dict) else set()
            if got_names != set(want):
                raise ValueError(
                    f"import: kv tensors {sorted(got_names)} != pool "
                    f"layout {sorted(want)} (cache_dtype {cache_dt})"
                )
            for name, (wshape, wdt) in want.items():
                arr = kv.get(name)
                shape = tuple(getattr(arr, "shape", ()))
                if shape != wshape:
                    raise ValueError(
                        f"import: kv[{name!r}] shape {shape} != pool "
                        f"geometry {wshape}"
                    )
                if jnp.dtype(getattr(arr, "dtype", None)) != wdt:
                    # wrong-dtype bytes pass the sha256 (it hashes what
                    # was sent) but would scatter garbage bit patterns
                    raise ValueError(
                        f"import: kv[{name!r}] dtype {arr.dtype} != pool "
                        f"dtype {wdt}"
                    )
            req.import_state = {
                "offset": offset, "cur": int(snap["cur"]), "kv": kv,
            }
        elif out:
            # re-prefill rung: the KV for prompt + out[:-1] is recomputed
            # locally; out[-1] is the resume token (its K/V is written by
            # the first decode forward, same as any freshly sampled token)
            seq = ids + out[:-1]
            if len(seq) + 1 >= self.max_seq_len:
                raise ValueError(
                    f"import: {len(seq)} accepted positions leave no room "
                    f"in max_seq_len={self.max_seq_len}"
                )
            req.import_state = {"seq": seq, "cur": out[-1], "kv": None}
        # else: nothing was ever decoded — a plain fresh admission
        self.scheduler.submit(req)
        return req

    def score(self, token_ids: list[int]):
        """Per-token logprobs of a sequence (no cache, full forward) — the
        scoring/training-parity path."""
        ids = jnp.asarray([token_ids], jnp.int32)
        logits, _ = core.forward(self.params, self.model_cfg, ids, None, jnp.int32(0))
        logprobs = jax.nn.log_softmax(logits[0, :-1], axis=-1)
        tgt = ids[0, 1:]
        return jax.device_get(jnp.take_along_axis(logprobs, tgt[:, None], axis=1)[:, 0])

    @property
    def info(self) -> dict:
        out = {
            "model": self.model_cfg.name,
            "n_params": int(
                sum(np.prod(x.shape) for x in jax.tree.leaves(self.params))
            ),
            "mesh": dict(self.mesh.shape),
            "dtype": str(self.dtype),
            "max_seq_len": self.max_seq_len,
            "platform": jax.devices()[0].platform,
        }
        out["kv"] = self.kv_info
        # speculative-decode observability (dashboards read acceptance to
        # judge whether the workload repeats enough to keep K up). Read
        # _scheduler directly — info() must not allocate the batch cache.
        sch = self._scheduler
        st = sch.stats if sch is not None else None
        drafted = st.spec_drafted if st else 0
        out["spec"] = {
            "spec_tokens": self.engine_cfg.spec_tokens,
            "drafted": drafted,
            "accepted": st.spec_accepted if st else 0,
            "acceptance": (
                round(st.spec_accepted / drafted, 4) if drafted else 0.0
            ),
        }
        # tiered drafting: per-tier split only when a drafter is
        # configured (the base dict shape above is pinned by tests and
        # the dashboards' scrape schema)
        if self.engine_cfg.drafter:
            out["spec"]["drafter"] = self.engine_cfg.drafter
            out["spec"]["tiers"] = dict(st.spec_tiers) if st else {}
        # multi-adapter serving: residency + pool churn (dashboards, the
        # mesh hello's service metadata, and the router's placement input
        # all read this through TPUService.get_metadata)
        if self.adapter_pool is not None:
            out["adapters"] = self.adapter_pool.info
        # engine economics plane (ISSUE 15): per-root compile counts,
        # MFU/goodput over the trailing window, and the HBM ledger —
        # refresh() also brings the engine.* economics gauges current
        out["introspect"] = self.introspect.refresh()
        return out
