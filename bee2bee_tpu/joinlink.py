"""Join-link codec: deep links that encode bootstrap addresses.

Capability parity with reference p2p.py (/root/reference/bee2bee/p2p.py:8-52):
`coithub.org://join?...`-style links with URL-safe-base64 bootstrap addrs,
sha256 helper, chunking and bitfield helpers. Links use the
`bee2bee-tpu://join?node=...&addrs=...` schema natively; the parser ALSO
accepts a verbatim reference-generated link (`network`/`model`/`hash` plus
repeated `bootstrap=<b64>` keys, reference p2p.py:8-15) so a node can join
a swarm advertised by either implementation.
"""

from __future__ import annotations

import base64
from urllib.parse import parse_qs, quote, urlparse

from .utils import sha256_hex

SCHEME = "bee2bee-tpu"


def _b64e(s: str) -> str:
    return base64.urlsafe_b64encode(s.encode("utf-8")).decode("ascii").rstrip("=")


def _b64d(s: str) -> str:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad).decode("utf-8")


def generate_join_link(node_id: str, bootstrap_addrs: list[str], name: str | None = None) -> str:
    """Encode node id + bootstrap WS addrs into a deep link
    (reference p2p.py:8-15)."""
    addrs = ",".join(_b64e(a) for a in bootstrap_addrs)
    link = f"{SCHEME}://join?node={quote(node_id)}&addrs={addrs}"
    if name:
        link += f"&name={quote(name)}"
    return link


def parse_join_link(link: str) -> dict:
    """Decode a join link → {node_id, bootstrap_addrs, name, ...}
    (reference p2p.py:18-36).

    Accepts both dialects:
    - native:    bee2bee-tpu://join?node=ID&addrs=<b64>,<b64>[&name=N]
    - reference: coithub.org://join?network=NET&model=M&hash=H
                 &bootstrap=<b64>&bootstrap=<b64>   (repeated keys,
                 reference p2p.py:8-15; scheme may also be `coithub`)
    Reference-dialect links surface their extra fields as `network`,
    `model`, `hash` so callers can route/verify; `node_id` falls back to
    the network name.
    """
    parsed = urlparse(link)
    if parsed.scheme not in (SCHEME, "coithub", "coithub.org", "https", "http"):
        raise ValueError(f"unrecognized join link scheme: {parsed.scheme!r}")
    qs = parse_qs(parsed.query)  # parse_qs already percent-decodes
    out: dict = {}
    if "bootstrap" in qs:  # reference dialect: one b64 addr per repeated key
        addrs = [_b64d(b) for b in qs["bootstrap"] if b]
        out["network"] = qs.get("network", [""])[0] or None
        out["model"] = qs.get("model", [""])[0] or None
        out["hash"] = qs.get("hash", [""])[0] or None
        node = qs.get("node", [""])[0] or out["network"] or ""
    else:
        raw_addrs = qs.get("addrs", [""])[0]
        addrs = [_b64d(a) for a in raw_addrs.split(",") if a]
        node = qs.get("node", [""])[0]
    name = qs.get("name", [""])[0] or None
    if not addrs:
        raise ValueError("join link has no bootstrap addresses")
    return {"node_id": node, "bootstrap_addrs": addrs, "name": name, **out}


def chunk_bytes(data: bytes, size: int) -> list[bytes]:
    """Split bytes into fixed-size chunks (reference p2p.py:43-44)."""
    if size <= 0:
        raise ValueError("chunk size must be positive")
    return [data[i : i + size] for i in range(0, len(data), size)] or [b""]


def bitfield_from_pieces(have: set[int] | list[int], total: int) -> bytes:
    """Pack piece-possession into a bitfield (reference p2p.py:47-52)."""
    have = set(have)
    out = bytearray((total + 7) // 8)
    for i in have:
        if 0 <= i < total:
            out[i // 8] |= 1 << (7 - (i % 8))
    return bytes(out)


def pieces_from_bitfield(bitfield: bytes, total: int) -> set[int]:
    out = set()
    for i in range(total):
        if bitfield[i // 8] & (1 << (7 - (i % 8))):
            out.add(i)
    return out


sha256_hex_bytes = sha256_hex  # reference name (p2p.py:39-40)
