"""Loopback WebSocket-compatible transport: the gate for a missing
`websockets` dependency.

Some minimal images (including CI containers) lack the `websockets`
package, which used to black out the ENTIRE mesh layer — node, pipeline,
failover, chaos, web tests all died at import. This module implements
the narrow slice of the websockets API the codebase uses over plain
asyncio streams, and `meshnet/node.py` / `web/bridge.py` fall back to it
when the real package is absent (same pattern as compat.py's jax shims).

Scope — read before extending:

- The wire format is a private length-prefixed framing (1-byte opcode:
  0 text / 1 binary, u64 little-endian length, payload), NOT RFC 6455.
  Both ends of a link must speak it, which is exactly the situation in
  tests and single-host dev meshes. With `websockets` installed this
  module is never imported, so real deployments keep real WebSockets
  (and wire compatibility with the reference's JS bridge).
- API covered: `serve(handler, host, port, max_size=...)` →
  `.sockets/.close()/.wait_closed()`; `connect(addr, max_size=...,
  open_timeout=...)` usable as `await` or `async with`; connection
  `.send(str|bytes)`, `.recv()`, `.close()`, async iteration;
  `ConnectionClosed` at module top level and under `.exceptions`.
- Close semantics are simplified: iteration ends (StopAsyncIteration)
  on ANY close, clean or not, and `recv()` raises ConnectionClosed.
  The mesh treats both identically (reader exit → drop peer).
"""

from __future__ import annotations

import asyncio
import struct
from urllib.parse import urlparse


class ConnectionClosed(Exception):
    """Connection is gone (mirrors websockets.exceptions.ConnectionClosed)."""


class ConnectionClosedOK(ConnectionClosed):
    pass


class ConnectionClosedError(ConnectionClosed):
    pass


class exceptions:  # namespace mirror: websockets.exceptions.ConnectionClosed
    ConnectionClosed = ConnectionClosed
    ConnectionClosedOK = ConnectionClosedOK
    ConnectionClosedError = ConnectionClosedError


_HDR = struct.Struct("<BQ")
_OP_TEXT, _OP_BINARY = 0, 1


class WSProto:
    """One connection end: send/recv/close + async iteration."""

    def __init__(self, reader, writer, max_size: int | None = None):
        self._reader = reader
        self._writer = writer
        self._max_size = max_size
        self.closed = False

    async def send(self, data) -> None:
        if self.closed or self._writer.is_closing():
            raise ConnectionClosedError("connection is closed")
        if isinstance(data, str):
            op, payload = _OP_TEXT, data.encode("utf-8")
        else:
            op, payload = _OP_BINARY, bytes(data)
        self._writer.write(_HDR.pack(op, len(payload)))
        self._writer.write(payload)
        try:
            await self._writer.drain()
        except (ConnectionError, OSError) as e:
            self.closed = True
            raise ConnectionClosedError(f"send failed: {e}") from e

    async def recv(self):
        try:
            op, n = _HDR.unpack(await self._reader.readexactly(_HDR.size))
            if self._max_size is not None and n > self._max_size:
                raise ConnectionClosedError(f"frame of {n} bytes exceeds max_size")
            payload = await self._reader.readexactly(n) if n else b""
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self.closed = True
            raise ConnectionClosed("connection closed") from e
        return payload.decode("utf-8") if op == _OP_TEXT else payload

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self.recv()
        except ConnectionClosed:
            raise StopAsyncIteration from None

    async def close(self) -> None:
        self.closed = True
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except Exception:  # noqa: BLE001 — closing a dead socket is fine
            pass


class Server:
    """Mirror of websockets' server handle over asyncio.start_server.
    Like the real package, close() takes down the listener AND every
    established connection (the mesh relies on that for shutdown)."""

    def __init__(self, server: asyncio.AbstractServer, conns: set):
        self._server = server
        self._conns = conns

    @property
    def sockets(self):
        return self._server.sockets

    def close(self) -> None:
        self._server.close()
        for ws in list(self._conns):
            ws.closed = True
            try:
                ws._writer.close()
            except Exception:  # noqa: BLE001 — already-dead transports
                pass

    async def wait_closed(self) -> None:
        await self._server.wait_closed()


async def serve(handler, host: str, port: int, max_size: int | None = None,
                **_kw) -> Server:
    conns: set[WSProto] = set()

    async def _cb(reader, writer):
        ws = WSProto(reader, writer, max_size)
        conns.add(ws)
        try:
            await handler(ws)
        except ConnectionClosed:
            pass
        finally:
            conns.discard(ws)
            await ws.close()

    return Server(await asyncio.start_server(_cb, host, port), conns)


class _Connect:
    """`connect(...)` result: awaitable AND an async context manager,
    like the real package's Connect object."""

    def __init__(self, addr: str, max_size: int | None = None,
                 open_timeout: float = 10, **_kw):
        self._addr = addr
        self._max_size = max_size
        self._open_timeout = open_timeout
        self._ws: WSProto | None = None

    async def _open(self) -> WSProto:
        u = urlparse(self._addr)
        if u.scheme != "ws":
            # no TLS here; callers' wss→ws fallback handles the downgrade
            raise OSError(f"wscompat supports ws:// only, got {self._addr!r}")
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(u.hostname, u.port),
            timeout=self._open_timeout,
        )
        self._ws = WSProto(reader, writer, self._max_size)
        return self._ws

    def __await__(self):
        return self._open().__await__()

    async def __aenter__(self) -> WSProto:
        return await self._open()

    async def __aexit__(self, *exc) -> None:
        if self._ws is not None:
            await self._ws.close()


def connect(addr: str, **kw) -> _Connect:
    return _Connect(addr, **kw)
