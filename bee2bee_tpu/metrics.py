"""Zero-dependency serving metrics: Counter / Gauge / Histogram + exposition.

The reference had no metrics surface beyond per-request ``latency_ms``
(SURVEY §5); the node's ``/metrics`` route served a handful of ad-hoc
gauges. This module is the real registry behind it:

- ``Counter`` / ``Gauge`` / ``Histogram`` with optional labels; histograms
  use FIXED log-spaced buckets (no per-value allocation, bounded memory,
  mergeable across scrapes) and can estimate percentiles from the bucket
  counts — good enough for TTFT/TPOT dashboards without a dependency.
- ``MetricsRegistry.render()`` emits Prometheus text exposition
  (``bee2bee_<name> …``); ``snapshot()`` is the JSON twin (and what
  bench.py embeds into BENCH_*.json).
- One process-global registry via ``get_registry()``; creation is
  idempotent so modules can hold module-level handles.

Never-throw guarantee: the record paths (``inc``/``set``/``observe``)
swallow bad values — telemetry must not take down the serving path
(same contract as tracing.Span). Metric NAMES are dotted literals
("engine.ttft_ms"); meshlint ML-T001 rejects dynamically-built names,
which is what keeps label/series cardinality bounded.
"""

from __future__ import annotations

import math
import threading

# fixed log-spaced latency buckets (milliseconds): 1 ms .. ~65 s, factor 2.
# 17 buckets + the implicit +Inf — wide enough for queue-wait through
# whole-generation latencies, coarse enough to stay cheap per observe.
DEFAULT_BUCKETS_MS = tuple(float(2 ** i) for i in range(17))


def log_buckets(lo: float, hi: float, factor: float = 2.0) -> tuple:
    """Log-spaced bucket upper bounds covering [lo, hi]."""
    out = [float(lo)]
    while out[-1] < hi:
        out.append(out[-1] * factor)
    return tuple(out)


def _labels_key(labels: dict) -> tuple:
    # fast paths for the hot-path shapes (`inc()`, `inc(op=...)`): the
    # per-frame mesh counters pay this on every send/receive, and the
    # generator + sorted() pipeline below is several times the cost of
    # the whole inc() otherwise
    if not labels:
        return ()
    if len(labels) == 1:
        ((k, v),) = labels.items()
        return ((k if type(k) is str else str(k), v if type(v) is str else str(v)),)
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: tuple, extra: tuple = ()) -> str:
    pairs = list(extra) + list(key)
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared base: per-metric lock + labeled series table."""

    kind = "untyped"

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    @property
    def prom_name(self) -> str:
        return "bee2bee_" + self.name.replace(".", "_").replace("-", "_")


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1.0, **labels) -> None:
        try:
            n = float(n)
            if not math.isfinite(n):
                return
            key = _labels_key(labels)
            with self._lock:
                self._series[key] = float(self._series.get(key, 0.0)) + n
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_labels_key(labels), 0.0))

    def bind(self, **labels):
        """Pre-resolve one labeled series; returns `inc(n=1.0)` for it.
        The per-frame mesh counters call inc() for every frame on the
        wire with the same label values — binding once hoists the
        label-key construction out of the hot path (the moral equivalent
        of prometheus clients' `counter.labels(...).inc()`)."""
        key = _labels_key(labels)

        def _inc(n: float = 1.0) -> None:
            try:
                with self._lock:
                    self._series[key] = float(self._series.get(key, 0.0)) + n
            except Exception:  # noqa: BLE001 — telemetry never throws
                pass

        return _inc

    def total(self) -> float:
        """Sum across every labeled series (the digest-friendly scalar)."""
        with self._lock:
            return float(sum(self._series.values()))

    def series(self) -> list[tuple[tuple, float]]:
        """[(labels_key, value)] — labels_key is the sorted (k, v) tuple."""
        with self._lock:
            return sorted(self._series.items())

    def render(self) -> list[str]:
        # prometheus convention: counters expose as <name>_total
        base = self.prom_name + "_total"
        with self._lock:
            items = sorted(self._series.items())
        lines = [f"# HELP {base} {self.help}" if self.help else f"# HELP {base} {self.name}",
                 f"# TYPE {base} counter"]
        if not items:
            items = [((), 0.0)]
        lines += [f"{base}{_fmt_labels(k)} {_fmt_value(v)}" for k, v in items]
        return lines

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._series.items())
        return {
            "type": "counter",
            "series": [{"labels": dict(k), "value": v} for k, v in items],
        }


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        try:
            v = float(v)
            if not math.isfinite(v):
                return
            with self._lock:
                self._series[_labels_key(labels)] = v
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def add(self, n: float = 1.0, **labels) -> None:
        try:
            n = float(n)
            if not math.isfinite(n):
                return
            key = _labels_key(labels)
            with self._lock:
                self._series[key] = float(self._series.get(key, 0.0)) + n
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_labels_key(labels), 0.0))

    def series(self) -> list[tuple[tuple, float]]:
        """[(labels_key, value)] — labels_key is the sorted (k, v) tuple."""
        with self._lock:
            return sorted(self._series.items())

    def clear(self, **labels) -> None:
        """Drop a series so the exposition omits it: a gauge whose source
        has no current reading (e.g. an empty rolling-latency window) must
        disappear rather than serve its last stale value forever."""
        try:
            with self._lock:
                self._series.pop(_labels_key(labels), None)
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def render(self) -> list[str]:
        base = self.prom_name
        with self._lock:
            items = sorted(self._series.items())
        lines = [f"# HELP {base} {self.help}" if self.help else f"# HELP {base} {self.name}",
                 f"# TYPE {base} gauge"]
        # no synthetic 0 sample when nothing was ever set / all cleared:
        # unlike counters (0 is meaningful), a fabricated gauge reading
        # would be indistinguishable from a real measurement of 0
        lines += [f"{base}{_fmt_labels(k)} {_fmt_value(v)}" for k, v in items]
        return lines

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._series.items())
        return {
            "type": "gauge",
            "series": [{"labels": dict(k), "value": v} for k, v in items],
        }


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # + the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str = "", buckets: tuple | None = None):
        super().__init__(name, help_)
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS_MS)))
        if not bs:
            bs = DEFAULT_BUCKETS_MS
        self.buckets = bs

    def observe(self, v: float, **labels) -> None:
        try:
            v = float(v)
            if not math.isfinite(v):
                return
            key = _labels_key(labels)
            with self._lock:
                s = self._series.get(key)
                if s is None:
                    s = self._series[key] = _HistSeries(len(self.buckets))
                i = 0
                while i < len(self.buckets) and v > self.buckets[i]:
                    i += 1
                s.counts[i] += 1
                s.sum += v
                s.count += 1
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def percentile(self, q: float, **labels) -> float:
        """Bucket-resolution percentile estimate: the upper bound of the
        bucket where the cumulative count crosses q (the +Inf bucket
        reports the top finite bound — an estimate, clearly biased up to
        one bucket width, which log spacing keeps proportional)."""
        with self._lock:
            s = self._series.get(_labels_key(labels))
            if s is None or s.count == 0:
                return 0.0
            target = q * s.count
            cum = 0
            for i, c in enumerate(s.counts):
                cum += c
                if cum >= target:
                    return self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
            return self.buckets[-1]

    def series_count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_labels_key(labels))
            return s.count if s else 0

    def totals(self, **labels) -> tuple[int, float]:
        """(observation count, value sum) for one series."""
        with self._lock:
            s = self._series.get(_labels_key(labels))
            return (s.count, s.sum) if s else (0, 0.0)

    def count_le(self, v: float, **labels) -> int:
        """Observations that landed in buckets whose upper bound is <= v
        (bucket resolution: an off-bound v rounds DOWN to the nearest
        bound, so the answer never overcounts — what SLO good-event
        counting needs from a bucketed histogram)."""
        with self._lock:
            s = self._series.get(_labels_key(labels))
            if s is None:
                return 0
            cum = 0
            for i, b in enumerate(self.buckets):
                if b > v:
                    break
                cum += s.counts[i]
            else:
                if v == math.inf:
                    cum += s.counts[-1]
            return cum

    def render(self) -> list[str]:
        base = self.prom_name
        with self._lock:
            items = sorted(
                (k, list(s.counts), s.sum, s.count)
                for k, s in self._series.items()
            )
        lines = [f"# HELP {base} {self.help}" if self.help else f"# HELP {base} {self.name}",
                 f"# TYPE {base} histogram"]
        for key, counts, total, count in items:
            cum = 0
            for i, b in enumerate(list(self.buckets) + [math.inf]):
                cum += counts[i]
                lines.append(
                    f"{base}_bucket"
                    f"{_fmt_labels(key, (('le', _fmt_value(b)),))} {cum}"
                )
            lines.append(f"{base}_sum{_fmt_labels(key)} {_fmt_value(round(total, 6))}")
            lines.append(f"{base}_count{_fmt_labels(key)} {count}")
        return lines

    def snapshot(self, percentiles: tuple = (0.5, 0.95, 0.99)) -> dict:
        with self._lock:
            keys = list(self._series)
        series = []
        for key in keys:
            with self._lock:
                s = self._series.get(key)
                if s is None:
                    continue
                count, total = s.count, s.sum
            entry = {"labels": dict(key), "count": count, "sum": round(total, 6)}
            for q in percentiles:
                entry[f"p{int(q * 100)}"] = self.percentile(q, **dict(key))
            series.append(entry)
        return {"type": "histogram", "buckets": list(self.buckets), "series": series}


class MetricsRegistry:
    """Thread-safe named-metric table; creation is idempotent so modules
    hold module-level handles (`_H = get_registry().histogram("x.y")`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_make(self, cls, name: str, help_: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_, **kw)
            elif not isinstance(m, cls):
                # a kind collision is a CODE bug, not a runtime hazard —
                # raise at registration so tests catch it immediately
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, help_)

    def histogram(
        self, name: str, help_: str = "", buckets: tuple | None = None
    ) -> Histogram:
        return self._get_or_make(Histogram, name, help_, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        """Registered metric by name WITHOUT creating it — readers (the
        health digest, SLO evaluation) must not materialize series for
        subsystems this process never imported."""
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every metric."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self, percentiles: tuple = (0.5, 0.95, 0.99)) -> dict:
        """JSON view: {dotted_name: {type, series, ...}}."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: dict[str, dict] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                out[m.name] = m.snapshot(percentiles)
            else:
                out[m.name] = m.snapshot()
        return out

    def reset_all(self) -> None:
        """Zero every registered metric IN PLACE. Modules bind metric
        handles at import time (`_C = get_registry().counter(...)`), so
        swapping the registry object would leave those handles writing
        into the old one — the only way to get a clean slate (simnet
        needs one between same-seed replays so telemetry digests match
        bit-for-bit) is to clear the series tables the handles share."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                m._series.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
