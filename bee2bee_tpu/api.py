"""HTTP gateway: per-node REST API over the mesh runtime.

Same surface as the reference's FastAPI app (api.py:113-267): `GET /` status,
`GET /peers`, `GET /providers`, `POST /connect`, `POST /chat` + `/generate`
(alias) with local-first fuzzy model match, streaming via chunked responses,
and P2P fallback; `X-API-KEY` auth — but DENIED BY DEFAULT when no key is
configured locally-only (the reference leaves the API wide open with no key,
api.py:24-26; here an unset key only allows loopback callers). Built on
aiohttp (fastapi/uvicorn are not in this image).
"""

from __future__ import annotations

import asyncio
import contextlib
import hmac
import json
import logging
import os
from typing import Any

from aiohttp import web

import math

from . import __version__
from . import health
from .adapters import (
    AdapterPoolBusy,
    UnknownAdapter,
    clamp_adapter_name,
    split_model_adapter,
)
from .health import fleet_view, render_fleet_prom
from .meshnet.node import P2PNode
from .metrics import PROMETHEUS_CONTENT_TYPE, get_registry
from .obs import SERIES_BY_NAME, SERIES_NAMES
from .protocol import copy_sampling
from .router import DEFAULT_TENANT, AdmissionReject
from .tracing import get_tracer, stitch_trace

logger = logging.getLogger("bee2bee_tpu.api")

# node-level gauges refreshed at scrape time. Names match the pre-registry
# /metrics exposition exactly (dashboards already scrape them); gauges, not
# counters, because the Prometheus counter convention appends _total and
# would rename the series.
_REG = get_registry()
_G_TOKENS_PER_SEC = _REG.gauge(
    "tokens_per_sec", "measured serving throughput (rolling)"
)
_G_TOTAL_TOKENS = _REG.gauge("total_tokens", "tokens served since boot")
_G_TOTAL_REQUESTS = _REG.gauge("total_requests", "requests served since boot")
_G_PEERS = _REG.gauge("peers", "connected mesh peers")
_G_PROVIDERS = _REG.gauge("providers", "remote services known")
_G_LOCAL_SERVICES = _REG.gauge("local_services", "services hosted locally")
_G_PIECES = _REG.gauge("pieces", "weight pieces stored")
_G_CPU = _REG.gauge("cpu_percent", "host CPU utilization")
_G_ACCEL_MEM = _REG.gauge(
    "accelerator_mem_percent", "accelerator memory utilization"
)
_G_P50_LATENCY = _REG.gauge(
    "p50_latency_seconds", "rolling p50 request latency"
)


def _cors_headers(api_key: str | None) -> dict[str, str]:
    """CORS policy. The reference always sends `*` (api.py:92-98) — but
    combined with our loopback-only keyless auth that would let any page in
    the operator's browser drive the node. So: browsers are only allowed
    when an origin list is configured explicitly, or when requests must
    carry an API key anyway (which a drive-by page doesn't have)."""
    origin = os.environ.get("BEE2BEE_CORS_ORIGINS") or ("*" if api_key else None)
    if not origin:
        return {}
    return {
        "Access-Control-Allow-Origin": origin,
        "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
        "Access-Control-Allow-Headers": "Content-Type, X-API-KEY, Authorization",
    }


def _int_param(body: dict, keys: tuple[str, ...], default: int) -> int:
    """First present-and-not-None key wins; an explicit 0 stays 0."""
    for k in keys:
        v = body.get(k)
        if v is not None:
            return int(v)
    return default


def _presented_key(request: web.Request) -> str:
    """The credential the caller sent: X-API-KEY, or the Bearer token
    (standard OpenAI SDKs send the key that way on /v1)."""
    key = request.headers.get("X-API-KEY", "")
    if key:
        return key
    auth = request.headers.get("Authorization", "")
    if auth.startswith("Bearer "):
        return auth[len("Bearer "):]
    return ""


def _auth_ok(request: web.Request, api_key: str | None, tenants=None) -> bool:
    # constant-time comparisons: == leaks matching-prefix length via
    # timing on the SDK-facing /v1 surface. Compare utf-8 bytes —
    # compare_digest raises TypeError on non-ASCII str input, which
    # would turn a bad header into a 500 instead of a 401
    enc = lambda s: s.encode("utf-8", "surrogateescape")
    presented = _presented_key(request)
    if api_key and hmac.compare_digest(enc(presented), enc(api_key)):
        return True
    # per-tenant API keys (router/tenants.py) authenticate too — tenant
    # identity FLOWS from the key, so a tenant key must open the door it
    # is billed through (resolve_key is constant-time per key)
    if tenants is not None and tenants.resolve_key(presented) is not None:
        return True
    if api_key:
        return False
    # no node key configured: loopback only (safer than the reference's
    # open default, per SURVEY §7 "what NOT to carry over")
    peer = request.remote or ""
    return peer in ("127.0.0.1", "::1", "localhost", "")


def _tenant_of(request: web.Request, tenants) -> str:
    """Tenant billed for this request: the one owning the presented API
    key, else the default tenant (weight 1, no budget)."""
    if tenants is None:
        return DEFAULT_TENANT
    return tenants.resolve_key(_presented_key(request)) or DEFAULT_TENANT


def _admission_response(rej: AdmissionReject, cors, v1: bool = False):
    """Typed 429/503 response: Retry-After header + error_kind /
    retry_after_s body — the contract docs/SERVING.md documents and
    client.MeshOverloaded parses."""
    if v1:
        body = {"error": {
            "message": rej.detail, "type": "overloaded_error",
            "error_kind": rej.kind, "retry_after_s": rej.retry_after_s,
        }}
    else:
        body = {"detail": rej.detail, "error_kind": rej.kind,
                "retry_after_s": rej.retry_after_s}
    return web.json_response(
        body,
        status=rej.status,
        headers={**dict(cors), "Retry-After": str(max(1, math.ceil(rej.retry_after_s)))},
    )


# local service resolution lives on the node (_local_service_for) so the
# HTTP gateway and the P2P gen_request path share one matching rule


def build_app(node: P2PNode, api_key: str | None = None) -> web.Application:
    app = web.Application(client_max_size=32 * 1024 * 1024)
    app["node"] = node
    cors = _cors_headers(api_key)

    @web.middleware
    async def middleware(request: web.Request, handler):
        if request.method == "OPTIONS":
            return web.Response(headers=cors)
        if not _auth_ok(request, api_key, node.tenants):
            return web.json_response(
                {"detail": "invalid or missing X-API-KEY"}, status=401, headers=cors
            )
        try:
            resp = await handler(request)
        except web.HTTPException:
            raise
        except ConnectionResetError:
            raise  # client went away mid-stream; nothing to respond to
        except AdmissionReject as rej:
            # a typed shed from ANY depth — this node's admission or a
            # remote hop's rejection surfaced by request_generation —
            # keeps its 429/503 + Retry-After contract instead of
            # collapsing into the generic 500 below
            return _admission_response(
                rej, cors, v1=request.path.startswith("/v1")
            )
        except UnknownAdapter as e:
            # the eviction-races-admission window (the pre-admission
            # ensure_adapter check covers the common path): still a typed
            # 404, never a 500
            if request.path.startswith("/v1"):
                body = {"error": {"message": str(e),
                                  "type": "invalid_request_error",
                                  "error_kind": "unknown_adapter"}}
            else:
                body = {"detail": str(e), "error_kind": "unknown_adapter"}
            return web.json_response(body, status=404, headers=cors)
        except AdapterPoolBusy as e:
            # a VALID adapter hitting a slot-saturated pool is
            # backpressure, not absence: the pool_exhausted 503 +
            # Retry-After shed (clients retry; a 404 they would not)
            return _admission_response(
                AdmissionReject(
                    "pool_exhausted",
                    node.admission.config.shed_retry_after_s,
                    f"adapter pool busy: {e}",
                ),
                cors, v1=request.path.startswith("/v1"),
            )
        except Exception as e:
            if request.transport is None:
                raise  # response already started and connection is gone
            logger.exception("handler error")
            return web.json_response({"detail": str(e)}, status=500, headers=cors)
        for k, v in cors.items():
            resp.headers.setdefault(k, v)
        return resp

    app.middlewares.append(middleware)

    async def home(request):
        st = node.status()
        st.update({"status": "ok", "version": __version__})
        return web.json_response(st)

    async def peers(request):
        out = []
        for pid, info in node.peers.items():
            out.append(
                {
                    "peer_id": pid,
                    "addr": info.get("addr"),
                    "region": info.get("region"),
                    "health": info.get("health"),
                    "rtt_ms": info.get("rtt_ms"),
                    "metrics": info.get("metrics"),
                    "api_port": info.get("api_port"),
                }
            )
        return web.json_response({"peers": out})

    async def providers(request):
        return web.json_response({"providers": node.list_providers(request.query.get("model"))})

    async def connect(request):
        body = await _json_body(request)
        target = body.get("addr") or body.get("link")
        if not target:
            return web.json_response({"detail": "addr or link required"}, status=400)
        ok = await node.connect_bootstrap(target)
        return web.json_response({"connected": ok})

    async def _admit_and_serve_local(request, svc, params, stream, sse=None):
        """THE admission contract on the HTTP surface, shared by /chat and
        /v1: acquire a slot (WDRR-queued by tenant when saturated) →
        stream or execute → bill the tenant's completed tokens → release.
        Raises AdmissionReject for the middleware's typed 429/503 +
        Retry-After response; returns the StreamResponse (streaming) or
        the service result dict."""
        ticket = await node.admission.acquire(
            params["tenant"], cost_tokens=params["max_new_tokens"]
        )
        try:
            if stream:
                return await _stream_service(
                    request, node, svc, params, cors, sse=sse, ticket=ticket
                )
            # node._execute_local = executor dispatch + gen.local span
            # with contextvar parenting (engine spans nest under it)
            result = await node._execute_local(
                svc, params, stream=False, on_chunk=None
            )
            ticket.note_tokens(result.get("tokens") or 0)
            return result
        finally:
            ticket.release()

    async def chat(request):
        body = await _json_body(request)
        prompt = body.get("prompt") or _prompt_from_messages(body.get("messages"))
        if not prompt:
            return web.json_response({"detail": "prompt or messages required"}, status=400)
        model = body.get("model")
        with get_tracer().span(
            "api.chat", model=model, stream=bool(body.get("stream"))
        ):
            return await _chat_inner(request, body, prompt, model)

    def _resolve_model(model, tenant):
        """(svc, base model, adapter, affinity) for one request. The
        "<base>:<adapter>" grammar applies ONLY where a colon can mean
        an adapter — the base resolves to an adapter-pooled engine
        service: a backend whose OWN ids contain colons (ollama
        "llama3:8b") advertised verbatim keeps serving them whole.
        Within the grammar, the explicit model form wins, else the
        tenant's configured default adapter (router/tenants.py) — the
        one-base-many-tenants mapping every surface shares. A malformed
        adapter half raises UnknownAdapter (the middleware's typed 404)
        — never a silent fall-through to the plain base. `adapter` is
        what this node COMMITS to (params + ensure_adapter); `affinity`
        only scores the provider pick when nothing local resolves and
        the serving node must re-derive from the forwarded model id."""
        base_model, raw = split_model_adapter(model)
        if raw is None:
            svc = node.local_service_for(base_model)
            adapter = node.tenants.default_adapter(tenant)
            if adapter and svc is not None and not P2PNode.adapter_capable(svc):
                adapter = None  # a default can't apply to this backend
            return svc, base_model, adapter, adapter
        svc = node.local_service_for(base_model)
        if svc is not None and P2PNode.adapter_capable(svc):
            adapter = clamp_adapter_name(raw)
            if adapter is None:
                raise UnknownAdapter(
                    f"malformed adapter name in model {model!r}"
                )
            return svc, base_model, adapter, adapter
        verbatim = node.service_advertising(model)
        if verbatim is not None:
            # the colon belongs to the backend's own tag grammar
            return verbatim, model, None, None
        if svc is not None:
            # the base resolves locally but cannot serve adapters: the
            # typed 404 (a pool-less engine must never silently serve
            # the plain base under an adapter-qualified id)
            raise UnknownAdapter(
                f"service for {base_model!r} cannot serve adapter "
                f"models ({model!r})"
            )
        # nothing local either way: forward the ORIGINAL id whole; the
        # split half only biases the provider pick toward residents
        return None, model, None, clamp_adapter_name(raw)

    async def _chat_inner(request, body, prompt, model):
        params = {
            "prompt": prompt,
            "max_new_tokens": _int_param(body, ("max_new_tokens", "max_tokens"), 2048),
            "temperature": float(body.get("temperature", 0.7)),
        }
        # the full sampling surface rides through to the service layer —
        # silently dropping a requested penalty would be wrong output, not
        # a degraded default
        copy_sampling(body, params)
        stream = bool(body.get("stream"))
        tenant = _tenant_of(request, node.tenants)
        params["tenant"] = tenant
        svc, base_model, adapter, affinity = _resolve_model(model, tenant)
        if adapter:
            params["adapter"] = adapter

        if svc is not None:
            if adapter and not await node.ensure_adapter(svc, adapter):
                # typed 404: the adapter neither is resident nor could be
                # paged in from the mesh — a wrong name must not serve
                # the plain base model silently
                return web.json_response(
                    {"detail": f"unknown adapter {adapter!r} for model "
                               f"{base_model!r}",
                     "error_kind": "unknown_adapter"}, status=404
                )
            out = await _admit_and_serve_local(request, svc, params, stream)
            if isinstance(out, web.StreamResponse):
                return out
            return web.json_response(out)

        # P2P fallback (reference api.py:247-264): prefix-aware scored pick
        provider = node.pick_provider(
            model, prompt=prompt, adapter=adapter or affinity
        )
        if provider is None or provider["local"]:
            return web.json_response(
                {"detail": f"no provider for model {model!r}"}, status=404
            )
        if stream:
            return await _stream_p2p(
                request, node, provider, params, model, cors, tenant=tenant
            )
        result = await node.request_generation(
            provider["provider_id"],
            prompt,
            model=model,
            max_new_tokens=params["max_new_tokens"],
            temperature=params["temperature"],
            extra=_sampling_extra(params),
            tenant=tenant,
        )
        return web.json_response(result)

    async def trace(request):
        """Observability surface the reference lacks (SURVEY §5).

        - default: per-span percentiles + recent spans.
        - ``?trace_id=``: this node's local FRAGMENT of one trace —
          {"node", "trace_id", "spans"} (spans share the id across every
          hop the request touched, thanks to wire trace propagation).
        - ``?trace_id=&stitch=1``: additionally query every peer that
          advertises an api port for ITS fragment and merge them into one
          cross-node timeline (tracing.stitch_trace). Best-effort: peers
          that are unreachable or require a key we don't hold are skipped.
        """
        tracer = get_tracer()
        trace_id = request.query.get("trace_id")
        if trace_id:
            frag = {
                "node": node.peer_id,
                "trace_id": trace_id,
                "spans": tracer.for_trace(trace_id),
            }
            if not request.query.get("stitch"):
                return web.json_response(frag)
            import aiohttp

            async def fetch_fragment(s, pid, host, port):
                """A peer that can't answer (or answers garbage) becomes a
                typed PARTIAL fragment, so stitch_trace reports it under
                missing_peers instead of silently shrinking the timeline."""
                try:
                    async with s.get(
                        f"http://{host}:{port}/trace",
                        params={"trace_id": trace_id},
                        timeout=aiohttp.ClientTimeout(total=3),
                    ) as r:
                        if r.status == 200:
                            got = await r.json()
                            if isinstance(got, dict) and isinstance(
                                got.get("spans"), list
                            ):
                                return got
                            return {"node": pid, "partial": True}
                except Exception:  # noqa: BLE001 — stitch what answers
                    pass
                return {"node": pid, "unreachable": True}

            # concurrent fan-out: N unreachable peers cost ONE 3s timeout,
            # not 3s each — a stitch over a big mesh must stay interactive.
            # A peer with no advertised API endpoint can't be asked at all:
            # it lands in missing_peers too, so the stitch never reports
            # complete while silently lacking that node's spans.
            tasks, no_endpoint = [], []
            for pid, info in list(node.peers.items()):
                if info.get("api_host") and info.get("api_port"):
                    tasks.append(
                        (pid, info.get("api_host"), info.get("api_port"))
                    )
                else:
                    no_endpoint.append({"node": pid, "unreachable": True})
            async with aiohttp.ClientSession() as s:
                got = await asyncio.gather(*(
                    fetch_fragment(s, pid, host, port)
                    for pid, host, port in tasks
                ))
            return web.json_response(
                stitch_trace([frag] + list(got) + no_endpoint)
            )
        try:
            limit = min(1000, max(1, int(request.query.get("limit", 50))))
        except ValueError:
            return web.json_response({"detail": "limit must be an int"}, status=400)
        return web.json_response(
            {
                "stats": tracer.stats(),
                "recent": tracer.recent(limit, name=request.query.get("name")),
            }
        )

    def _refresh_node_gauges():
        from . import utils

        snap = node.throughput.snapshot()
        # None: one snapshot is enough — only cpu/gpu are read from sysm
        sysm = utils.get_system_metrics(None)
        _G_TOKENS_PER_SEC.set(snap.get("tokens_per_sec", 0.0))
        _G_TOTAL_TOKENS.set(snap.get("total_tokens", 0))
        _G_TOTAL_REQUESTS.set(snap.get("total_requests", 0))
        _G_PEERS.set(len(node.peers))
        _G_PROVIDERS.set(sum(len(v) for v in node.providers.values()))
        _G_LOCAL_SERVICES.set(len(node.local_services))
        _G_PIECES.set(len(node.piece_store))
        _G_CPU.set(sysm.get("cpu", 0.0))
        _G_ACCEL_MEM.set(sysm.get("gpu", 0.0))
        p50 = snap.get("p50_latency_s")
        if p50 is not None:
            _G_P50_LATENCY.set(p50)
        else:
            # the rolling window is empty: drop the series rather than
            # serve the last measured p50 as if it were current (the
            # pre-registry exposition omitted the line in this case too)
            _G_P50_LATENCY.clear()
        # pipeline stage idleness (ISSUE 10): bee2bee_pipeline_bubble_
        # fraction is DERIVED from the tracer's stage.task spans, so a
        # scrape recomputes it over the trailing window (and clears it
        # when this node served no stage traffic — never-throw inside)
        health.local_stage_idleness()
        # engine economics (ISSUE 15): MFU/goodput/HBM-ledger gauges are
        # provider-derived the same way — refresh them at scrape time
        health.run_digest_providers()

    async def metrics(request):
        """The node's metrics registry (metrics.py): Prometheus text
        exposition by default — node gauges plus every registered serving
        series (TTFT/inter-token/queue-wait histograms, block-pool
        occupancy, mesh frame counters, ...). Content-negotiated:
        ``?format=json`` or ``Accept: application/json`` returns the JSON
        snapshot (bucket counts + estimated percentiles) instead."""
        _refresh_node_gauges()
        reg = get_registry()
        fmt = request.query.get("format")
        accept = request.headers.get("Accept", "")
        if fmt == "json" or (fmt is None and "application/json" in accept):
            return web.json_response(
                {"node": node.peer_id, "metrics": reg.snapshot()}
            )
        return web.Response(
            body=reg.render().encode("utf-8"),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    # ---- health plane (health.py): the fleet view, SLO status, and the
    # incident flight recorder — the surface the SLO-aware front door
    # (ROADMAP item 3) scrapes/routes on.

    async def mesh_health(request):
        """Merged fleet view: this node's live digest + every FRESH peer
        digest from telemetry gossip, with fleet aggregates. JSON default;
        ``?format=prom`` (or ``Accept: text/plain``) renders Prometheus
        text with one series per fresh peer under a ``peer`` label —
        stale peers' series drop out rather than serving forever."""
        view = fleet_view(
            node.peer_id, node.telemetry_digest(), node.health,
            # scope the fleet aggregate block to the controller's actual
            # replica universe — the endpoint must show the same numbers
            # a scale decision reads, not count every gossiping node
            serving=node.fleet.serving_peers(),
        )
        fmt = request.query.get("format")
        accept = request.headers.get("Accept", "")
        if fmt == "prom" or (fmt is None and "text/plain" in accept):
            return web.Response(
                body=render_fleet_prom(view).encode("utf-8"),
                headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
            )
        return web.json_response(view)

    def _platform_stamp() -> str:
        """Best-effort accelerator platform for /metrics/history, so
        benchdiff --live can apply the PR 6 cross-platform refusal. Reads
        jax only if something else already imported it — a control-plane
        node must not pay a jax import for a telemetry stamp."""
        import sys as _sys

        jax = _sys.modules.get("jax")
        if jax is not None:
            try:
                return jax.devices()[0].platform
            except Exception:  # noqa: BLE001 — stamp is best-effort
                pass
        return "unknown"

    def _parse_history_query(request):
        """(names, window_s) shared by /metrics/history + /mesh/history;
        raises web.HTTPBadRequest with a typed body on garbage."""
        names_q = (request.query.get("series") or "").strip()
        names = None
        if names_q:
            names = [n.strip() for n in names_q.split(",") if n.strip()]
            unknown = sorted(n for n in names if n not in SERIES_BY_NAME)
            if unknown:
                raise web.HTTPBadRequest(
                    text=json.dumps({
                        "detail": f"unknown series: {unknown}",
                        "known": list(SERIES_NAMES),
                    }),
                    content_type="application/json",
                )
        try:
            window_s = float(request.query.get("window", 3600.0))
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"detail": "window must be a number"}),
                content_type="application/json",
            )
        return names, window_s

    async def metrics_history(request):
        """The observatory's retained time-series (obs/tsring.py):
        ``?series=a,b`` restricts to named series (400 on unknown names),
        ``?window=`` trims to the trailing seconds (default 3600), and
        the payload is delta-encoded by default — ``?format=raw`` returns
        plain ``[[ts, value], ...]`` points instead. The ``platform``
        stamp lets scripts/benchdiff.py --live refuse cross-platform
        comparisons, same rule as recorded artifacts."""
        names, window_s = _parse_history_query(request)
        raw = request.query.get("format") == "raw"
        return web.json_response({
            "node": node.peer_id,
            "cadence_s": node.obs.cadence_s,
            "window_s": window_s,
            "retained": len(node.obs.ring),
            "platform": _platform_stamp(),
            "encoding": "raw" if raw else "delta",
            "series": node.obs.history(names, window_s, raw=raw),
        })

    async def mesh_history(request):
        """Fleet-level curves: this node's retained history merged with
        every connected peer's (fetched from their /metrics/history —
        same best-effort fan-out as /trace?stitch=1: unreachable peers
        and peers with no advertised API endpoint are typed, never
        silently dropped). The ``fleet`` block buckets all reporters
        onto the sampling-cadence grid and aggregates each series by its
        catalog rule — throughput sums, levels average."""
        names, window_s = _parse_history_query(request)
        peers_out: dict[str, dict] = {
            node.peer_id: {"series": node.obs.history(names, window_s, raw=True)}
        }
        import aiohttp

        async def fetch_history(s, pid, host, port):
            try:
                params = {"window": str(window_s), "format": "raw"}
                if names:
                    params["series"] = ",".join(names)
                async with s.get(
                    f"http://{host}:{port}/metrics/history",
                    params=params,
                    timeout=aiohttp.ClientTimeout(total=3),
                ) as r:
                    if r.status == 200:
                        got = await r.json()
                        if isinstance(got, dict) and isinstance(
                            got.get("series"), dict
                        ):
                            return pid, {"series": got["series"]}
            except Exception:  # noqa: BLE001 — merge what answers
                pass
            return pid, {"unreachable": True}

        tasks = []
        for pid, info in list(node.peers.items()):
            if info.get("api_host") and info.get("api_port"):
                tasks.append((pid, info["api_host"], info["api_port"]))
            else:
                peers_out[pid] = {"no_endpoint": True}
        if tasks:
            async with aiohttp.ClientSession() as s:
                got = await asyncio.gather(*(
                    fetch_history(s, pid, host, port)
                    for pid, host, port in tasks
                ))
            peers_out.update({pid: entry for pid, entry in got})
        cadence = node.obs.cadence_s
        fleet: dict[str, list] = {}
        for name in (names or SERIES_NAMES):
            spec = SERIES_BY_NAME[name]
            buckets: dict[int, list[float]] = {}
            for entry in peers_out.values():
                for point in (entry.get("series") or {}).get(name) or []:
                    try:
                        t, v = float(point[0]), float(point[1])
                    except (TypeError, ValueError, IndexError):
                        continue
                    buckets.setdefault(int(t // cadence), []).append(v)
            if not buckets:
                continue
            fleet[name] = [
                [
                    round(b * cadence, 3),
                    round(
                        sum(vs) if spec.agg == "sum" else sum(vs) / len(vs), 6
                    ),
                ]
                for b, vs in sorted(buckets.items())
            ]
        return web.json_response({
            "node": node.peer_id,
            "cadence_s": cadence,
            "window_s": window_s,
            "agg": {n: SERIES_BY_NAME[n].agg for n in (names or SERIES_NAMES)},
            "peers": peers_out,
            "fleet": fleet,
        })

    async def slo(request):
        """Per-objective SLO status: a FRESH burn-rate evaluation (also
        refreshes the bee2bee_slo_* gauges served by /metrics)."""
        return web.json_response(
            {
                "node": node.peer_id,
                "windows": {
                    "fast_s": node.slo.fast_window_s,
                    "slow_s": node.slo.slow_window_s,
                },
                "trip_burn_rate": node.slo.trip_burn_rate,
                "objectives": node.slo.status(),
            }
        )

    async def admin_drain(request):
        """Graceful drain (docs/ROBUSTNESS.md "Live migration & drain"):
        flips the node to draining — new requests 503 typed ``draining``
        with Retry-After, the drain state rides the telemetry digest so
        peers stop routing here — and migrates every in-flight generation
        to scored-healthy peers (KV export; re-prefill fallback). Body:
        ``{"stop": true}`` additionally exits the node with a clean
        GOODBYE once the last bridged stream finishes; ``{"wait": false}``
        returns immediately with ``pending`` instead of blocking until
        the bridged generations complete (long generations can hold the
        default waiting response open for minutes — poll GET /admin/drain
        for progress then).

        ADMIN surface: the first destructive action the API exposes.
        Tenant API keys (which open the serving routes) do NOT open it —
        only the node key, or loopback when no key is configured
        (_auth_ok with tenants=None is exactly that rule)."""
        if not _auth_ok(request, api_key, None):
            return web.json_response(
                {"detail": "drain requires the node API key"},
                status=403, headers=cors,
            )
        body = {}
        if request.can_read_body:
            with_suppress = False
            try:
                body = await request.json()
            except (json.JSONDecodeError, UnicodeDecodeError):
                with_suppress = True
            if with_suppress or not isinstance(body, dict):
                return web.json_response(
                    {"detail": "invalid JSON body"}, status=400
                )
        summary = await node.begin_drain(
            stop=bool(body.get("stop")),
            wait=bool(body.get("wait", True)),
        )
        return web.json_response(summary)

    async def admin_drain_status(request):
        return web.json_response({
            "draining": node.draining,
            "migration": dict(node.migration.stats),
        })

    async def fleet_status(request):
        """Elastic fleet control surface (fleet/controller.py): lease
        view, leader role, latest controller aggregates, the bounded
        decision journal (noops included — the operator sees WHY nothing
        happened), in-flight action and config."""
        return web.json_response(node.fleet.status())

    async def fleet_override(request):
        """Manual override (docs/ROBUSTNESS.md "Elastic fleet control"):
        body ``{"action": "scale_out"|"scale_in"|"pause"|"resume",
        "target": <peer_id, optional>}``. Scale actions bypass the
        hysteresis but NOT the probe gate or the one-in-flight rule, and
        only the lease holder runs them (409 points at the leader).
        ADMIN surface, same rule as /admin/drain: tenant keys do not
        open it."""
        if not _auth_ok(request, api_key, None):
            return web.json_response(
                {"detail": "fleet override requires the node API key"},
                status=403, headers=cors,
            )
        body = await _json_body(request)
        action = body.get("action")
        if not action:
            return web.json_response({"detail": "action required"}, status=400)
        out = await node.fleet.override(
            str(action), target=body.get("target")
        )
        if out.get("ok"):
            return web.json_response(out)
        status = 409 if out.get("error") in (
            "not_leader", "action_in_flight"
        ) else 400
        return web.json_response(out, status=status)

    async def debug_incidents(request):
        """Flight-recorder surface: ``?id=<incident id>`` fetches one full
        on-disk bundle; otherwise the newest-first bundle index plus the
        live ring tail (the events an incident WOULD snapshot right now)."""
        inc_id = request.query.get("id")
        if inc_id:
            # bundle reads hit disk — off the event loop, same reasoning
            # as the recorder's threaded write path
            bundle = await asyncio.to_thread(node.recorder.load_incident, inc_id)
            if bundle is None:
                return web.json_response(
                    {"detail": f"unknown incident {inc_id!r}"}, status=404
                )
            return web.json_response(bundle)
        try:
            limit = min(500, max(1, int(request.query.get("ring", 50))))
        except ValueError:
            return web.json_response({"detail": "ring must be an int"}, status=400)
        return web.json_response(
            {
                "node": node.peer_id,
                "incidents": await asyncio.to_thread(node.recorder.list_incidents),
                "ring": node.recorder.events(limit=limit),
            }
        )

    async def debug_profile(request):
        """On-demand device profiling (docs/OBSERVABILITY.md "Engine
        economics"): POST starts a duration-bounded ``jax.profiler``
        capture (body ``{"duration_s": 2.0}``, clamped to the profiler's
        max) and blocks until the zipped artifact lands under
        ``$BEE2BEE_INCIDENT_DIR/profiles``; a concurrent capture is the
        typed 409 ``profile_in_progress`` (jax.profiler is a process
        singleton — two captures would corrupt each other). GET lists
        artifacts newest-first like /debug/incidents; ``?id=`` streams
        one zip.

        ADMIN surface, same rule as /admin/drain: a device profile leaks
        whole-node execution detail, so tenant keys do not open it."""
        from .engine.introspect import ProfileInProgress, get_profiler

        # the admin gate covers the WHOLE surface — the GET listing and
        # ?id= zip download leak the same whole-node execution detail the
        # POST produces, so a tenant key must not open them either
        if not _auth_ok(request, api_key, None):
            return web.json_response(
                {"detail": "device profiling requires the node API key"},
                status=403, headers=cors,
            )
        profiler = get_profiler()
        if request.method == "GET":
            prof_id = request.query.get("id")
            if prof_id:
                path = await asyncio.to_thread(profiler.profile_path, prof_id)
                if path is None:
                    return web.json_response(
                        {"detail": f"unknown profile {prof_id!r}"}, status=404
                    )
                # streamed, not buffered: a long TPU capture's zip can be
                # hundreds of MB — exactly the memory pressure the
                # operator is profiling
                return web.FileResponse(
                    path,
                    headers={
                        "Content-Type": "application/zip",
                        "Content-Disposition":
                            f'attachment; filename="{prof_id}.zip"',
                    },
                )
            return web.json_response({
                "node": node.peer_id,
                "profiles": await asyncio.to_thread(profiler.list_profiles),
                "active": profiler.active,
            })
        body = await _json_body(request) if request.can_read_body else {}
        if not isinstance(body, dict):
            return web.json_response(
                {"detail": "invalid JSON body"}, status=400
            )
        try:
            duration = float(body.get("duration_s", 2.0))
        except (TypeError, ValueError):
            return web.json_response(
                {"detail": "duration_s must be a number"}, status=400
            )
        try:
            # capture blocks ~duration_s: off the event loop, bounded by
            # the profiler's own MAX_DURATION_S clamp
            header = await asyncio.to_thread(profiler.capture, duration)
        except ProfileInProgress as e:
            return web.json_response(
                {"detail": str(e), "error_kind": "profile_in_progress"},
                status=409,
            )
        return web.json_response(header)

    # ---- OpenAI-compatible surface (/v1): standard SDKs and tools can
    # point at a mesh node unchanged (base_url="http://node:4002/v1").
    # Completions/chat map onto the same local-first + P2P-fallback path
    # as /chat; streaming uses SSE with OpenAI chunk objects.

    async def v1_models(request):
        names = set()
        # list_providers(None) already includes every LOCAL service's
        # metadata alongside mesh providers — one matching rule, one loop
        for prov in node.list_providers(None):
            names.update(prov.get("models") or [])
        return web.json_response({
            "object": "list",
            "data": [
                {"id": n, "object": "model", "owned_by": "bee2bee-tpu"}
                for n in sorted(names)
            ],
        })

    def _openai_params(body, prompt):
        params = {
            "prompt": prompt,
            "max_new_tokens": _int_param(body, ("max_tokens", "max_new_tokens"), 256),
            "temperature": float(body.get("temperature", 1.0)),
        }
        copy_sampling(body, params)
        return params

    def _openai_response(result, model, chat: bool):
        text = result.get("text", "")
        completion_tokens = int(result.get("tokens", 0))
        prompt_tokens = int(result.get("prompt_tokens", 0))
        choice = {
            "index": 0,
            "finish_reason": result.get("finish_reason", "stop"),
        }
        if chat:
            choice["message"] = {"role": "assistant", "content": text}
        else:
            choice["text"] = text
        return {
            "id": f"cmpl-{os.urandom(8).hex()}",
            "object": "chat.completion" if chat else "text_completion",
            "model": model or "",
            "choices": [choice],
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens,
            },
        }

    async def _v1_generate(request, body, prompt, chat: bool):
        model = body.get("model")
        params = _openai_params(body, prompt)
        sse = ("chat" if chat else "text", model or "")
        tenant = _tenant_of(request, node.tenants)
        params["tenant"] = tenant
        # model="<base>:<adapter>" (multi-adapter serving, adapters/):
        # standard OpenAI SDKs select a tenant adapter purely through the
        # model id; a tenant's configured default applies otherwise
        svc, base_model, adapter, affinity = _resolve_model(model, tenant)
        if adapter:
            params["adapter"] = adapter
        if svc is not None:
            if adapter and not await node.ensure_adapter(svc, adapter):
                return web.json_response(
                    {"error": {
                        "message": f"model {model!r} not found "
                                   f"(unknown adapter {adapter!r})",
                        "type": "invalid_request_error",
                        "error_kind": "unknown_adapter",
                    }}, status=404)
            result = await _admit_and_serve_local(
                request, svc, params, bool(body.get("stream")), sse=sse
            )
            if isinstance(result, web.StreamResponse):
                return result
        else:
            provider = node.pick_provider(
                model, prompt=prompt, adapter=adapter or affinity
            )
            if provider is None or provider["local"]:
                return web.json_response(
                    {"error": {"message": f"model {model!r} not found",
                               "type": "invalid_request_error"}}, status=404)
            if bool(body.get("stream")):
                return await _stream_p2p(
                    request, node, provider, params, model, cors, sse=sse,
                    tenant=tenant,
                )
            result = await node.request_generation(
                provider["provider_id"], prompt, model=model,
                max_new_tokens=params["max_new_tokens"],
                temperature=params["temperature"],
                extra=_sampling_extra(params),
                tenant=tenant,
            )
        return web.json_response(_openai_response(result, model, chat))

    async def v1_completions(request):
        body = await _json_body(request)
        prompt = body.get("prompt")
        if isinstance(prompt, list):  # OpenAI allows a list of prompts
            if len(prompt) != 1:
                return web.json_response(
                    {"error": {"message": "only a single prompt is supported",
                               "type": "invalid_request_error"}}, status=400)
            prompt = prompt[0]
        if not prompt:
            return web.json_response(
                {"error": {"message": "prompt required",
                           "type": "invalid_request_error"}}, status=400)
        with get_tracer().span("api.v1.completions", model=body.get("model")):
            return await _v1_generate(request, body, prompt, chat=False)

    async def v1_chat_completions(request):
        body = await _json_body(request)
        prompt = _prompt_from_messages(body.get("messages"))
        if not prompt:
            return web.json_response(
                {"error": {"message": "messages required",
                           "type": "invalid_request_error"}}, status=400)
        # no assistant cue here: services that parse transcripts append it
        # themselves (TPUService._gen_args) — adding one would double it
        with get_tracer().span("api.v1.chat", model=body.get("model")):
            return await _v1_generate(request, body, prompt, chat=True)

    app.router.add_get("/", home)
    app.router.add_get("/peers", peers)
    app.router.add_get("/providers", providers)
    app.router.add_get("/trace", trace)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/metrics/history", metrics_history)
    app.router.add_get("/mesh/health", mesh_health)
    app.router.add_get("/mesh/history", mesh_history)
    app.router.add_get("/slo", slo)
    app.router.add_get("/debug/incidents", debug_incidents)
    app.router.add_get("/debug/profile", debug_profile)
    app.router.add_post("/debug/profile", debug_profile)
    app.router.add_post("/admin/drain", admin_drain)
    app.router.add_get("/admin/drain", admin_drain_status)
    app.router.add_get("/fleet", fleet_status)
    app.router.add_post("/fleet/override", fleet_override)
    app.router.add_post("/connect", connect)
    app.router.add_post("/chat", chat)
    app.router.add_post("/generate", chat)  # alias (reference api.py:190-191)
    app.router.add_get("/v1/models", v1_models)
    app.router.add_post("/v1/completions", v1_completions)
    app.router.add_post("/v1/chat/completions", v1_chat_completions)
    app.router.add_route("OPTIONS", "/{tail:.*}", lambda r: web.Response(headers=cors))
    return app


def _sampling_extra(params: dict) -> dict:
    extra = copy_sampling(params, {})
    if params.get("adapter"):
        # the adapter selection must survive the P2P hop like any
        # sampling knob — the serving node resolves it against its pool
        extra["adapter"] = params["adapter"]
    return extra


async def _json_body(request: web.Request) -> dict[str, Any]:
    try:
        return await request.json()
    except (json.JSONDecodeError, UnicodeDecodeError):
        raise web.HTTPBadRequest(reason="invalid JSON body")


def _prompt_from_messages(messages) -> str | None:
    """OpenAI-style messages → user:/assistant: transcript (the format the
    reference UI sends, App.jsx:994-998). Content may be the standard
    content-parts array — the text parts are joined (feeding the model a
    list repr would be silent garbage)."""
    if not messages:
        return None

    def text_of(content) -> str:
        if isinstance(content, list):
            return "".join(
                p.get("text", "") for p in content
                if isinstance(p, dict) and p.get("type") in (None, "text")
            )
        return "" if content is None else str(content)

    return "\n".join(
        f"{m.get('role', 'user')}: {text_of(m.get('content'))}" for m in messages
    )


def _make_frame(sse):
    """Line framer for the two stream transports: identity (ndjson) or an
    OpenAI SSE encoder when sse=("chat"|"text", model). Service error
    lines become an SSE error event + [DONE] — a swallowed error would be
    indistinguishable from a short completion."""
    if sse is None:
        return lambda line: line.encode("utf-8")
    kind, model = sse
    sse_id = f"cmpl-{os.urandom(8).hex()}"
    obj_name = "chat.completion.chunk" if kind == "chat" else "text_completion"

    def frame(line: str) -> bytes:
        try:
            obj = json.loads(line)
        except ValueError:
            obj = None
        if not isinstance(obj, dict):
            # a custom service streaming plain-text (or scalar-JSON) lines
            # must not lose output on /v1 — forward the raw line as a
            # delta chunk
            obj = {"text": line}
        if obj.get("status") == "error" or obj.get("error"):
            err = {"error": {"message": obj.get("message") or obj.get("error")
                             or "generation failed", "type": "server_error"}}
            return (f"data: {json.dumps(err)}\n\ndata: [DONE]\n\n").encode()
        if obj.get("done"):
            fin = {"index": 0, "finish_reason": obj.get("finish_reason", "stop")}
            fin["delta" if kind == "chat" else "text"] = {} if kind == "chat" else ""
            payload = {"id": sse_id, "model": model, "object": obj_name,
                       "choices": [fin]}
            return (f"data: {json.dumps(payload)}\n\ndata: [DONE]\n\n").encode()
        text = obj.get("text")
        if not text:
            return b""
        ch = {"index": 0, "finish_reason": None}
        if kind == "chat":
            ch["delta"] = {"content": text}
        else:
            ch["text"] = text
        payload = {"id": sse_id, "model": model, "object": obj_name,
                   "choices": [ch]}
        return f"data: {json.dumps(payload)}\n\n".encode()

    return frame


async def _stream_service(
    request, node: P2PNode, svc, params, cors=(), sse=None, ticket=None
) -> web.StreamResponse:
    """Streaming from a local service: JSON-lines by default, or OpenAI
    SSE chunks when sse=("chat"|"text", model) (the /v1 surface)."""
    import asyncio
    import contextvars
    import threading

    ctype = "text/event-stream" if sse else "application/x-ndjson"
    frame = _make_frame(sse)

    resp = web.StreamResponse(
        headers={"Content-Type": ctype, **dict(cors)}
    )
    await resp.prepare(request)
    loop = asyncio.get_running_loop()
    q: asyncio.Queue = asyncio.Queue()
    DONE = object()
    cancelled = threading.Event()

    def pump():
        try:
            for line in svc.execute_stream(params):
                if cancelled.is_set():
                    break  # client went away: stop pulling from the engine
                loop.call_soon_threadsafe(q.put_nowait, line)
        finally:
            loop.call_soon_threadsafe(q.put_nowait, DONE)

    # span + copy_context mirror node._execute_local (the service lines pass
    # through verbatim here, so we can't reuse it directly)
    import time as _time

    with get_tracer().span("gen.local", service=svc.name, stream=True) as span:
        ctx = contextvars.copy_context()
        task = loop.run_in_executor(None, ctx.run, pump)
        chunks = 0
        text_chars = 0
        t0 = _time.time()
        try:
            while True:
                item = await q.get()
                if item is DONE:
                    break
                chunks += 1
                try:  # count streamed text for the node's measured throughput
                    obj = json.loads(item)
                    text_chars += len(obj.get("text") or "")
                    # the span must tell the request's story, not just its
                    # setup: real token count + timing ride the done line,
                    # service failures ride error lines (ISSUE 5 satellite)
                    if obj.get("done"):
                        if obj.get("tokens") is not None:
                            span.attrs["tokens"] = int(obj["tokens"])
                            if ticket is not None:
                                # per-tenant completed-token accounting
                                # must not exclude streaming traffic
                                ticket.note_tokens(int(obj["tokens"]))
                        if obj.get("timing") is not None:
                            span.attrs["timing"] = obj["timing"]
                    if obj.get("status") == "error":
                        span.error = str(obj.get("message") or "stream error")
                except (ValueError, AttributeError, TypeError):
                    # metrics must never kill a stream: non-object lines or
                    # non-string "text" from custom services pass through
                    pass
                await resp.write(frame(item))
            await resp.write_eof()
        except (ConnectionResetError, asyncio.CancelledError):
            logger.info("stream client disconnected; aborting generation pump")
            raise
        finally:
            span.attrs["chunks"] = chunks
            cancelled.set()
            await task
            # node-level measured throughput must not miss the streaming
            # path (chars/4 = the reference's own token estimate)
            node.throughput.record(max(0, text_chars // 4), _time.time() - t0)
    return resp


async def _stream_p2p(
    request, node: P2PNode, provider, params, model, cors=(), sse=None,
    tenant=None,
) -> web.StreamResponse:
    import asyncio

    frame = _make_frame(sse)
    q: asyncio.Queue = asyncio.Queue()

    def on_chunk(text):
        q.put_nowait(json.dumps({"text": text}) + "\n")

    gen_task = asyncio.create_task(
        node.request_generation(
            provider["provider_id"],
            params["prompt"],
            model=model,
            max_new_tokens=params["max_new_tokens"],
            temperature=params["temperature"],
            stream=True,
            on_chunk=on_chunk,
            extra=_sampling_extra(params),
            tenant=tenant,
        )
    )
    resp = None
    getter = asyncio.create_task(q.get())
    try:
        while True:
            done, _ = await asyncio.wait({getter, gen_task}, return_when=asyncio.FIRST_COMPLETED)
            if resp is None:
                # the FIRST event decides the response: a failure arriving
                # before any chunk (typed remote shed, dead provider) must
                # surface as a real HTTP status — the middleware turns an
                # AdmissionReject into 429/503 + Retry-After — not as a 200
                # whose body smuggles an error line no backoff logic reads
                if getter not in done and gen_task.exception() is not None:
                    raise gen_task.exception()
                resp = web.StreamResponse(
                    headers={
                        "Content-Type": (
                            "text/event-stream" if sse else "application/x-ndjson"
                        ),
                        **dict(cors),
                    }
                )
                await resp.prepare(request)
            if getter in done:
                await resp.write(frame(getter.result()))
                getter = asyncio.create_task(q.get())
                continue
            # cancel BEFORE draining: a live q.get() would steal a chunk
            # from the post-completion drain below
            getter.cancel()
            try:
                await gen_task
                while not q.empty():
                    await resp.write(frame(q.get_nowait()))
                await resp.write(frame(json.dumps({"done": True}) + "\n"))
            except Exception as e:
                # mid-stream failure: the 200 is already on the wire — the
                # in-stream error line is all that's left to say
                await resp.write(
                    frame(json.dumps({"status": "error", "message": str(e)}) + "\n")
                )
            break
        await resp.write_eof()
        return resp
    finally:
        # an abandoned stream (client hung up: resp.prepare/write raises,
        # or aiohttp cancels the handler) must not leave the generation
        # decoding to its token budget for nobody, nor a q.get() task
        # dangling for the GC to cancel
        if not getter.done():
            getter.cancel()
        if not gen_task.done():
            gen_task.cancel()
            with contextlib.suppress(BaseException):
                await gen_task


async def start_api_server(node: P2PNode, host: str, port: int, api_key: str | None = None):
    """Start the gateway; returns the aiohttp AppRunner (await .cleanup())."""
    runner = web.AppRunner(build_app(node, api_key=api_key))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("api gateway on http://%s:%s", host, port)
    return runner
