"""Live generation migration: KV-block export/import over the mesh.

The production alternative to "start over" (ROADMAP item 2): a node can
ship an in-flight generation's complete recoverable state — block-table
rows, the referenced pool blocks as hashed tensor pieces, sampling
state, accepted tokens — to a scored-healthy peer, which imports the
blocks straight into its own paged pool and resumes decoding
token-for-token. No re-prefill on the happy path (pinned by the
scheduler's ``import_reprefills`` counter staying at zero). Three
consumers share the primitive:

- **graceful drain** (``drain()``, behind ``POST /admin/drain``): the
  node flips to draining (admission 503s new work typed ``draining``,
  the flag rides the telemetry digest so RouterPolicy excludes it),
  in-flight generations migrate out, and the node can exit clean with a
  GOODBYE;
- **disaggregated prefill→decode**: a prefill-designated node
  (``BEE2BEE_DISAGG=prefill``) offers every freshly prefilled row to the
  hook and ships it to a decode-designated peer — prefill compute and
  decode batching stop competing for the same chip;
- **migration-based failover**: a row the local pool can no longer grow
  (mid-decode exhaustion) migrates instead of erroring.

Wire protocol (protocol.py, analysis/schema.py): ``KV_EXPORT`` carries
the generation snapshot (scheduler ``_snapshot_meta``), the engine's
pool-compat signature and the chunk count; ``KV_BLOCKS`` frames carry
the pool blocks as binary tensor frames with per-buffer sha256 (the
pieces.py discipline — a corrupt block is refused before it touches the
target pool; an int8 pool ships its k_scale/v_scale tensors alongside
the pages at half the page bytes, hashed and verified the same way);
``KV_IMPORT_ACK`` is the target's typed verdict. The signature's
``cache_dtype`` gates layout compatibility: a bf16-pool node refuses an
int8 exporter's pages typed ``incompatible``, and the ladder then takes
the layout-free re-prefill rung — on the SAME peer if need be. The
resumed stream rides the existing GEN_CHUNK / GEN_SUCCESS / GEN_ERROR
plumbing under the migration rid, and the source BRIDGES it into the
original Request's event queue — the consumer (HTTP stream, p2p
requester) never notices the handoff.

Fallback ladder, every rung typed (docs/ROBUSTNESS.md): KV migration →
re-prefill migration (prompt + accepted recomputed on the target, the
PR 2 discipline) → typed error to the consumer. Every failed rung
leaves a ``migration:<reason>`` incident bundle; the reason is part of
the kind, so the flight recorder's per-kind cooldown can never let one
failing path mask another (or an SLO trip).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import queue as _queue

import numpy as np

from .. import protocol
from ..clock import get_clock
from ..health import get_recorder
from ..metrics import get_registry
from ..router import AdmissionReject
from ..tracing import get_tracer, inject_trace
from ..utils import TaskTracker, log_task_exception, new_id, sha256_hex

logger = logging.getLogger("bee2bee_tpu.migrate")

# migration observability: role in {out, in}, outcome a closed set
_C_MIGRATIONS = get_registry().counter(
    "mesh.migrations", "generation migrations by role and outcome"
)
_H_MIGRATION_MS = get_registry().histogram(
    "mesh.migration_export_ms",
    "export-to-resume-ack latency per migration (ms)",
)

# one KV_BLOCKS frame stays well under protocol.MAX_FRAME (32 MiB)
MAX_CHUNK_BYTES = 8 * 1024 * 1024

# the closed failure-reason set: every failure is incident kind
# "migration:<code>", so the recorder's per-kind cooldown is per-CAUSE —
# a burning hash_mismatch path cannot mask a pool_exhausted one, and
# none of them mask slo:* trips (different kinds entirely)
REASON_CODES = frozenset({
    "no_target",        # no scored-healthy peer serves the model
    "export_failed",    # the export frames never left / send raised
    "ack_timeout",      # the target never answered KV_IMPORT_ACK
    "hash_mismatch",    # a KV_BLOCKS piece failed sha256 verification
    "pool_exhausted",   # the target's pool couldn't host the blocks
    "incompatible",     # pool signature / snapshot validation mismatch
    "import_rejected",  # target admission (draining, shedding) said no
    "import_failed",    # the target engine failed after accepting
    "stream_lost",      # the resume stream died mid-generation
    "unrecoverable",    # every rung failed; the consumer got a typed error
})


class MigrationError(RuntimeError):
    """One failed migration rung; ``code`` indexes REASON_CODES."""

    def __init__(self, code: str, detail: str = "", target: str | None = None):
        super().__init__(detail or code)
        self.code = code if code in REASON_CODES else "import_rejected"
        self.detail = detail
        self.target = target


class _Bridge:
    """Source-side adapter: remote resume-stream frames → the ORIGINAL
    Request's event queue. Tokens run through the original ``accept()`` /
    ``text_delta()`` machinery, so stop/budget semantics and the
    UTF-8-safe incremental decode are byte-identical to a local rollout
    (the remote applies the same rules, so the two never disagree)."""

    def __init__(self, req, svc, loop):
        self.req = req
        self.svc = svc
        self.done: asyncio.Future = loop.create_future()
        self.new_tokens = 0

    def feed_chunk(self, data: dict) -> None:
        req = self.req
        if req.cancelled:
            # the consumer abandoned the stream mid-migration: stop
            # booking tokens for it. Known limitation: no cancel frame
            # reaches the target, so the remote still decodes its
            # (budget-bounded) tail — see docs/ROBUSTNESS.md.
            if req.finish is None:
                req.finish = "cancelled"
            return
        emitted: list[int] = []
        for t in data.get("tokens") or []:
            if not req.accept(int(t)):
                break
            emitted.append(int(t))
            if req.done:
                break
        self.new_tokens += len(emitted)
        if emitted and req.stream:
            req.events.put({
                "token": emitted[-1],
                "tokens": emitted,
                "text": req.text_delta(final=req.done),
            })

    def feed_result(self, data: dict) -> None:
        if self.done.done():
            return
        if data.get("error"):
            self.done.set_exception(
                MigrationError("import_failed", str(data["error"]))
            )
        else:
            self.done.set_result(data)

    def fail(self, exc: Exception) -> None:
        if not self.done.done():
            self.done.set_exception(exc)


class _PendingImport:
    """Target-side state for one in-flight KV_EXPORT."""

    __slots__ = ("rid", "ws", "gen", "svc", "expected", "chunks", "t0")

    def __init__(self, rid, ws, gen, svc, expected):
        self.rid = rid
        self.ws = ws
        self.gen = gen
        self.svc = svc
        self.expected = expected
        self.chunks: list[tuple[int, dict]] = []
        self.t0 = get_clock().monotonic()


class MigrationManager:
    """Per-node migration plane: source-side export/bridge/fallback and
    target-side import/serve, plus the drain coordinator. Lives on the
    node's event loop; the only cross-thread entry is the scheduler hook
    installed by ``wire_scheduler`` (which merely schedules loop work)."""

    def __init__(self, node, ack_timeout_s: float = 30.0,
                 bridge_timeout_s: float = 600.0):
        self.node = node
        self.clock = getattr(node, "clock", None) or get_clock()
        self.ack_timeout_s = ack_timeout_s
        self.bridge_timeout_s = bridge_timeout_s
        # bench/chaos knob: skip the KV rung and exercise re-prefill
        self.force_reprefill = False
        self._closed = False
        # source side
        self._acks: dict[str, asyncio.Future] = {}
        self._bridges: dict[str, _Bridge] = {}
        self._rid_ws: dict[str, object] = {}
        self._tasks = TaskTracker("migration")  # strong refs + crash logging
        # target side
        self._imports: dict[str, _PendingImport] = {}
        self.stats = {
            "migrated_out": 0, "migrated_in": 0, "fallback_reprefills": 0,
            "forwarded": 0, "failed": 0,
        }

    # ------------------------------------------------------------ wiring

    def wire_scheduler(self, svc) -> None:
        """Install the migration hook on an engine-backed service's
        scheduler (node.add_service calls this). The hook runs ON THE
        SCHEDULER THREAD: it only decides (target exists? loop alive?)
        and schedules the async migration; True transfers ownership of
        the request to this manager."""
        eng = getattr(svc, "engine", None)
        sch = getattr(eng, "scheduler", None) if eng is not None else None
        if sch is None:
            return
        node = self.node

        def cb(req, snap, reason) -> bool:
            loop = getattr(node, "_loop", None)
            if loop is None or loop.is_closed() or node._stopped or self._closed:
                return False
            decode_only = reason == "prefill_handoff"
            if not self.migration_targets(
                snap.get("model"), decode_only=decode_only
            ):
                return False
            kv = snap.pop("_kv", None)
            loop.call_soon_threadsafe(
                self.spawn_migration, req, svc, snap, kv, reason
            )
            return True

        sch.migrate_cb = cb
        if node.disagg_role == "prefill":
            sch.handoff_after_prefill = True

    def close(self) -> None:
        """node.stop(): fail outstanding bridges/acks so nothing awaits a
        reply that can no longer arrive."""
        self._closed = True
        err = MigrationError("stream_lost", "node stopped")
        for fut in self._acks.values():
            if not fut.done():
                fut.set_exception(err)
        for bridge in self._bridges.values():
            bridge.fail(err)
        self._imports.clear()

    # ------------------------------------------------------------ targets

    def migration_targets(self, model: str | None, exclude=(),
                          decode_only: bool = False) -> list[str]:
        """Peer ids that could host a migration: advertise a matching
        service AND have a fresh, non-draining telemetry digest (the
        "scored-healthy" requirement — a peer we know nothing about is
        not a place to ship live state).

        Called from the SCHEDULER THREAD too (the wire_scheduler hook):
        never-throw — a gossip-timing dict race must degrade to "no
        target", not escape into the scheduler loop's catch-all."""
        try:
            return self._migration_targets(model, exclude, decode_only)
        except Exception:  # noqa: BLE001
            logger.exception("migration target scan failed")
            return []

    def _migration_targets(self, model, exclude, decode_only) -> list[str]:
        fresh = self.node.health.fresh()
        out = []
        for pid, svcs in list(self.node.providers.items()):
            if pid in exclude:
                continue
            d = fresh.get(pid)
            if not isinstance(d, dict) or d.get("draining"):
                continue
            if d.get("fleet_state") in ("standby", "warming"):
                # an unprobed elastic-fleet replica must not receive
                # live state either — migrations are traffic
                continue
            if decode_only and d.get("disagg_role") != "decode":
                continue
            if d.get("disagg_role") == "draft":
                # a draft-role peer hosts ONLY the drafter model — it has
                # no target engine to resume a migrated generation on
                continue
            for meta in list(svcs.values()):
                models = [str(m) for m in (meta.get("models") or [])]
                if model is None or any(
                    model.lower() in m.lower() or m.lower() in model.lower()
                    for m in models
                ):
                    out.append(pid)
                    break
        return out

    def _pick_target(self, model: str | None, exclude: set,
                     decode_only: bool) -> str | None:
        cands = self.migration_targets(model, exclude, decode_only)
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        # telemetry-scored pick among the eligible set: reuse the router
        # by excluding everything that is NOT a migration candidate
        not_cands = set(self.node.providers) - set(cands)
        prov = self.node.pick_provider(
            model, remote_only=True, exclude=set(exclude) | not_cands
        )
        return prov["provider_id"] if prov is not None else cands[0]

    # ------------------------------------------------------- source side

    def spawn_migration(self, req, svc, snap: dict, kv, reason: str):
        """Entry from the scheduler hook (already on the loop)."""
        return self._tasks.spawn(
            self._migrate_with_fallback(req, svc, snap, kv, reason)
        )

    async def wait_idle(self, timeout_s: float = 60.0) -> bool:
        """Await in-flight source-side migrations (tests, drain-then-stop)."""
        deadline = self.clock.monotonic() + timeout_s
        while self._tasks and self.clock.monotonic() < deadline:
            with contextlib.suppress(Exception):
                await self.clock.wait_for(
                    asyncio.gather(*list(self._tasks), return_exceptions=True),
                    max(0.05, deadline - self.clock.monotonic()),
                )
        return not self._tasks

    async def _migrate_with_fallback(self, req, svc, snap: dict, kv,
                                     reason: str) -> str:
        """The fallback ladder. Returns the outcome: "ok" (KV rung),
        "reprefill", "forwarded" (queued request, nothing to resume) or
        "failed" (consumer got the typed error)."""
        t0 = self.clock.monotonic()
        excluded: set[str] = set()
        was_queued = not snap.get("out") and not snap.get("kv_blocks")
        with get_tracer().span(
            "mesh.migrate", reason=reason,
            accepted=len(snap.get("out") or []),
        ) as span:
            if kv is not None and not self.force_reprefill:
                try:
                    await self._migrate_once(
                        req, svc, snap, kv, reason,
                        excluded, decode_only=(reason == "prefill_handoff"),
                        t0=t0,
                    )
                    _C_MIGRATIONS.inc(role="out", outcome="ok")
                    self.stats["migrated_out"] += 1
                    span.attrs["outcome"] = "ok"
                    return "ok"
                except MigrationError as err:
                    self._incident(err, snap, reason)
                    # hash_mismatch indicts the PIECES (source/transit)
                    # and incompatible indicts the LAYOUT PAIRING (e.g. a
                    # bf16-pool peer refusing int8 pages, or a different
                    # kv_block_size) — neither indicts the target itself,
                    # so both stay eligible for the re-prefill rung,
                    # which ships token ids only and is layout-free
                    if err.target and err.code not in (
                        "hash_mismatch", "incompatible"
                    ):
                        excluded.add(err.target)
                except Exception as err:  # noqa: BLE001 — a rung bug must
                    # fall down the ladder, not escape the drain gather
                    logger.exception("KV migration rung crashed")
                    self._incident(
                        MigrationError("unrecoverable", repr(err)),
                        snap, reason,
                    )
            # a request the bridge already finished (accept() closed it;
            # only the remote's final frame was lost) needs no second
            # rung — shipping a COMPLETE generation somewhere just to
            # re-prefill and instantly retire it would be pure waste
            if req.finish is not None:
                try:
                    self._finalize(req, svc, {})
                    _C_MIGRATIONS.inc(role="out", outcome="ok")
                    self.stats["migrated_out"] += 1
                    span.attrs["outcome"] = "ok"
                    return "ok"
                except Exception:  # noqa: BLE001 — fall to the terminal
                    # path, which guards its own finalize
                    logger.exception("post-rung finalize failed")
            # re-prefill rung: the bridge may have advanced the output
            # before the stream died — re-snapshot the accepted tokens so
            # the target resumes from the true frontier, not a stale one
            try:
                snap2 = {**snap, "out": [int(t) for t in req.out_ids],
                         "kv_blocks": 0, "offset": 0, "cur": None}
                await self._migrate_once(
                    req, svc, snap2, None, reason, excluded,
                    decode_only=False, t0=t0,
                )
                if was_queued:
                    self.stats["forwarded"] += 1
                    _C_MIGRATIONS.inc(role="out", outcome="forwarded")
                    span.attrs["outcome"] = "forwarded"
                    return "forwarded"
                _C_MIGRATIONS.inc(role="out", outcome="reprefill")
                self.stats["fallback_reprefills"] += 1
                span.attrs["outcome"] = "reprefill"
                return "reprefill"
            except MigrationError as err:
                self._incident(err, snap, reason)
            except Exception as err:  # noqa: BLE001 — the consumer MUST
                # get a done event even on a manager bug
                logger.exception("migration fallback crashed")
                self._incident(
                    MigrationError("unrecoverable", repr(err)), snap, reason
                )
            # terminal: typed error, never a hung generation
            _C_MIGRATIONS.inc(role="out", outcome="failed")
            self.stats["failed"] += 1
            span.attrs["outcome"] = "failed"
            self._incident(
                MigrationError(
                    "unrecoverable",
                    f"every migration rung failed (reason={reason})",
                ),
                snap, reason,
            )
            # the consumer ALWAYS gets a done event — the no-hung-
            # generation contract. A req whose finish is already set
            # completed from the client's point of view (the bridge fed
            # every token and accept() closed it; only the remote's final
            # frame was lost): close it out as a success with the local
            # accounting instead of erroring a finished generation.
            if req.finish is not None:
                try:
                    self._finalize(req, svc, {})
                except Exception:  # noqa: BLE001 — last resort: a raw
                    # error event still unblocks the consumer
                    logger.exception("migration finalize failed")
                    req.events.put({
                        "done": True, "result": None,
                        "error": "migration_failed: finalize error",
                    })
            else:
                req.finish = "error"
                req.events.put({
                    "done": True, "result": None,
                    "error": "migration_failed: no peer could resume this "
                             "generation (see migration:* incidents)",
                })
            return "failed"

    async def _migrate_once(self, req, svc, snap: dict, kv, reason: str,
                            excluded: set, decode_only: bool, t0: float):
        """One rung: export to one target, await its typed ACK, bridge the
        resume stream to completion. Raises MigrationError."""
        target = self._pick_target(snap.get("model"), excluded, decode_only)
        if target is None:
            raise MigrationError(
                "no_target", "no scored-healthy peer serves this model"
            )
        info = self.node.peers.get(target)
        if info is None:
            raise MigrationError("no_target", f"peer {target} vanished", target)
        ws = info["ws"]
        rid = new_id("mig")
        loop = asyncio.get_running_loop()
        ack: asyncio.Future = loop.create_future()
        bridge = _Bridge(req, svc, loop)
        self._acks[rid] = ack
        self._bridges[rid] = bridge
        self._rid_ws[rid] = ws
        eng = getattr(svc, "engine", None)
        try:
            frames = self._encode_chunks(rid, kv) if kv is not None else []
            export = inject_trace(protocol.msg(
                protocol.KV_EXPORT,
                rid=rid,
                model=snap.get("model"),
                gen={k: v for k, v in snap.items() if not k.startswith("_")},
                sig=eng.migration_signature() if eng is not None else None,
                kv_chunks=len(frames),
                reason=reason,
            ))
            try:
                await self.node._send(ws, export)
                for seq, frame in enumerate(frames):
                    await self._send_chunk(ws, frame, seq)
            except Exception as err:
                raise MigrationError("export_failed", str(err), target)
            try:
                verdict = await self.clock.wait_for(ack, self.ack_timeout_s)
            except asyncio.TimeoutError:
                raise MigrationError(
                    "ack_timeout", f"no import ack from {target}", target
                )
            except MigrationError as err:
                err.target = err.target or target
                raise
            if not isinstance(verdict, dict) or not verdict.get("ok"):
                kind = (verdict or {}).get("error_kind") or "import_rejected"
                if kind not in REASON_CODES:
                    kind = "import_rejected"
                raise MigrationError(
                    kind, str((verdict or {}).get("error") or ""), target
                )
            _H_MIGRATION_MS.observe((self.clock.monotonic() - t0) * 1000.0)
            # resumed: bridge frames until the remote's final result
            try:
                wire = await self.clock.wait_for(
                    bridge.done, self.bridge_timeout_s
                )
            except asyncio.TimeoutError:
                raise MigrationError(
                    "stream_lost", "resume stream timed out", target
                )
            except MigrationError as err:
                err.target = err.target or target
                raise
            self._finalize(req, svc, wire)
        finally:
            self._acks.pop(rid, None)
            self._bridges.pop(rid, None)
            self._rid_ws.pop(rid, None)

    async def _send_chunk(self, ws, frame: bytes, seq: int) -> None:
        """One KV_BLOCKS frame — a seam chaos wraps (kill/corrupt)."""
        await self.node._send(ws, frame)

    def _encode_chunks(self, rid: str, kv: dict) -> list[bytes]:
        """Pool blocks → binary tensor frames, <= MAX_CHUNK_BYTES each,
        with per-buffer sha256 in the header (the pieces.py discipline).
        Generic over the pool's leaves: an int8 pool ships k/v pages AND
        their k_scale/v_scale tensors (block dim = axis 2 on every leaf),
        each hashed separately — a corrupt SCALE is as fatal to the
        import as a corrupt page and takes the same typed refusal."""
        arrs = {name: np.asarray(a) for name, a in kv.items()}
        nb = arrs["k"].shape[2]
        per_block = max(1, sum(a[:, :, :1].nbytes for a in arrs.values()))
        per = max(1, MAX_CHUNK_BYTES // per_block)
        frames = []
        starts = list(range(0, nb, per))
        for ci, s in enumerate(starts):
            part = {
                name: np.ascontiguousarray(a[:, :, s:s + per])
                for name, a in arrs.items()
            }
            frames.append(protocol.encode_binary(
                protocol.msg(
                    protocol.KV_BLOCKS,
                    rid=rid,
                    seq=ci,
                    done=(ci == len(starts) - 1),
                    hashes={
                        name: sha256_hex(p.tobytes())
                        for name, p in part.items()
                    },
                ),
                part,
            ))
        return frames

    def _finalize(self, req, svc, wire: dict) -> None:
        """The bridged generation finished remotely: close out the
        ORIGINAL request with a locally-built result (one decode pipeline,
        one accounting path — the consumer can't tell it migrated)."""
        if req.finish is None:
            fr = wire.get("finish_reason")
            req.finish = fr if isinstance(fr, str) and fr else "stop"
        req.timing.t_done = self.clock.monotonic()
        eng = getattr(svc, "engine", None)
        result = eng._build_result(req) if eng is not None else None
        req.events.put({"done": True, "result": result})

    def _incident(self, err: MigrationError, snap: dict, reason: str) -> None:
        get_recorder().incident(
            f"migration:{err.code}",
            detail=err.detail or err.code,
            node=self.node.peer_id,
            extra={
                "reason": reason,
                "target": err.target,
                "prompt_tokens": len(snap.get("ids") or []),
                "accepted_tokens": len(snap.get("out") or []),
            },
        )

    # ------------------------------------------------------ frame routing

    def feed_chunk(self, rid, data: dict) -> bool:
        """GEN_CHUNK router hook: True = this was a migration stream."""
        bridge = self._bridges.get(rid)
        if bridge is None:
            return False
        try:
            bridge.feed_chunk(data)
        except Exception:  # noqa: BLE001 — a bridge bug must not kill the reader
            logger.exception("migration bridge feed failed")
        return True

    def feed_result(self, rid, data: dict) -> bool:
        """GEN_SUCCESS/GEN_RESULT/GEN_ERROR router hook."""
        bridge = self._bridges.get(rid)
        if bridge is None:
            return False
        bridge.feed_result(data)
        return True

    def on_ws_drop(self, ws) -> None:
        """A connection died: fail every migration riding it (typed), and
        abandon target-side imports whose exporter is gone."""
        err = MigrationError("stream_lost", "peer connection lost")
        for rid, w in list(self._rid_ws.items()):
            if w is ws:
                fut = self._acks.get(rid)
                if fut is not None and not fut.done():
                    fut.set_exception(
                        MigrationError("stream_lost", "peer died before ack")
                    )
                bridge = self._bridges.get(rid)
                if bridge is not None:
                    bridge.fail(err)
        for rid, imp in list(self._imports.items()):
            if imp.ws is ws:
                self._imports.pop(rid, None)

    # ------------------------------------------------------- target side

    # a pending import whose exporter never finishes its chunk stream
    # (but keeps the connection alive) is abandoned after this long —
    # on_ws_drop handles the dead-connection case
    IMPORT_STALE_S = 120.0

    def _prune_stale_imports(self) -> None:
        now = self.clock.monotonic()
        for rid, imp in list(self._imports.items()):
            if now - imp.t0 > self.IMPORT_STALE_S:
                self._imports.pop(rid, None)
                logger.warning("abandoning stale KV import %s", rid)

    async def handle_export(self, ws, data: dict) -> None:
        self._prune_stale_imports()
        rid = data.get("rid")
        gen = data.get("gen")
        if not rid or not isinstance(gen, dict):
            return
        svc = (
            self.node.local_services.get(data.get("svc") or "")
            or self.node.local_service_for(data.get("model"))
        )
        eng = getattr(svc, "engine", None) if svc is not None else None
        if eng is None:
            await self._ack(ws, rid, ok=False,
                            error="no local engine serves this model",
                            error_kind="incompatible")
            return
        sig = data.get("sig")
        n_chunks = int(data.get("kv_chunks") or 0)
        if n_chunks > 0 and (
            not isinstance(sig, dict) or sig != eng.migration_signature()
        ):
            # a KV import needs a MATCHING signature: raw block bytes
            # scattering into a mismatched pool layout is silent
            # corruption, and sig-less blocks are refused outright.
            # Re-prefill imports (kv_chunks == 0) are deliberately exempt
            # — token ids are layout-free, and that rung is exactly how a
            # pool-incompatible mesh (different kv_block_size) still
            # evacuates generations.
            await self._ack(ws, rid, ok=False,
                            error="pool signature mismatch or missing",
                            error_kind="incompatible")
            return
        if n_chunks > getattr(eng, "blocks_per_row", n_chunks):
            # the chunk-count claim is wire input: each chunk carries at
            # least one block, so anything past the pool's per-row block
            # bound cannot be a legitimate export — refuse before the
            # buffering (handle_blocks bounds against this number)
            await self._ack(ws, rid, ok=False,
                            error=f"kv_chunks {n_chunks} exceeds pool bound",
                            error_kind="incompatible")
            return
        imp = _PendingImport(rid, ws, gen, svc, n_chunks)
        if n_chunks == 0:
            self._spawn_finish(imp, kv=None)
        else:
            self._imports[rid] = imp  # meshlint: ignore[ML-R003] -- rid-keyed: one import's export/blocks frames arrive on one connection reader, serialized

    def _spawn_finish(self, imp: _PendingImport, kv) -> None:
        """Admission may queue under saturation — never block the
        connection reader on it (pings/chunks must keep flowing)."""
        self._tasks.spawn(self._finish_import(imp, kv))

    async def handle_blocks(self, ws, data: dict) -> None:
        rid = data.get("rid")
        imp = self._imports.get(rid)
        if imp is None or imp.ws is not ws:
            return
        # the chunk stream is bounded by the declared count UP FRONT, not
        # only at the done frame: an exporter streaming past kv_chunks
        # (or retransmitting a seq — per-chunk hashes would still verify
        # a duplicate, silently corrupting the assembled pool image)
        # would otherwise buffer host tensors without limit
        seq = int(data.get("seq") or 0)
        if (
            len(imp.chunks) >= imp.expected
            or not 0 <= seq < imp.expected
            or any(s == seq for s, _ in imp.chunks)
        ):
            self._imports.pop(rid, None)
            await self._ack(
                ws, rid, ok=False,
                error=f"unexpected chunk seq {seq} "
                      f"({len(imp.chunks)}/{imp.expected} buffered)",
                error_kind="import_rejected",
            )
            return
        tensors = data.get("_tensors") or {}
        hashes = data.get("hashes") or {}
        names = sorted(hashes)
        if not {"k", "v"} <= set(names) or set(tensors) != set(names):
            # every shipped tensor must be hashed and every hash must
            # cover a shipped tensor — an unhashed scale (or a hashed
            # phantom) is a malformed export, not a verification pass
            self._imports.pop(rid, None)
            await self._ack(
                ws, rid, ok=False,
                error=f"chunk {seq}: tensor set {sorted(tensors)} != "
                      f"hash set {names}",
                error_kind="import_rejected",
            )
            return
        for name in names:
            arr = tensors.get(name)
            digest = hashes.get(name)
            if arr is None or digest is None or sha256_hex(
                np.ascontiguousarray(arr).tobytes()
            ) != digest:
                # a corrupt piece — page OR quantization scale — never
                # touches the pool: typed reject, the exporter's ladder
                # re-prefills elsewhere
                self._imports.pop(rid, None)
                _C_MIGRATIONS.inc(role="in", outcome="hash_mismatch")
                get_recorder().incident(
                    "migration:hash_mismatch",
                    detail=f"chunk {data.get('seq')} tensor {name!r} failed "
                           "verification",
                    node=self.node.peer_id,
                )
                await self._ack(
                    ws, rid, ok=False,
                    error=f"chunk {data.get('seq')} {name} hash mismatch",
                    error_kind="hash_mismatch",
                )
                return
        imp.chunks.append((seq, {name: tensors[name] for name in names}))
        if not data.get("done"):
            return
        self._imports.pop(rid, None)
        if len(imp.chunks) != imp.expected:
            await self._ack(
                ws, rid, ok=False,
                error=f"truncated export: {len(imp.chunks)} of "
                      f"{imp.expected} chunks",
                error_kind="import_rejected",
            )
            return
        imp.chunks.sort(key=lambda c: c[0])
        first_names = set(imp.chunks[0][1])
        if any(set(c[1]) != first_names for c in imp.chunks):
            await self._ack(
                ws, rid, ok=False,
                error="chunks disagree on tensor set",
                error_kind="import_rejected",
            )
            return
        kv = {
            name: np.concatenate([c[1][name] for c in imp.chunks], axis=2)
            for name in sorted(first_names)
        }
        self._spawn_finish(imp, kv)

    async def _finish_import(self, imp: _PendingImport, kv) -> None:
        gen = dict(imp.gen)
        # clamp the wire tenant claim like every other ingress
        tenant = self.node.tenants.clamp(gen.get("tenant"))
        gen["tenant"] = tenant
        remaining = max(
            1, int(gen.get("max_new_tokens") or 1) - len(gen.get("out") or [])
        )
        try:
            # bounded WELL below the exporter's ack_timeout_s: parking in
            # a saturated admission queue past it would make the exporter
            # give up and re-migrate elsewhere while we later decode the
            # whole generation for nobody (wait_for's cancellation runs
            # acquire's own bookkeeping/refund path)
            ticket = await self.clock.wait_for(
                self.node.admission.acquire(
                    tenant, cost_tokens=remaining, migration=True
                ),
                self.ack_timeout_s * 0.5,
            )
        except AdmissionReject as rej:
            await self._ack(imp.ws, imp.rid, ok=False, error=rej.detail,
                            error_kind=rej.kind)
            return
        except asyncio.TimeoutError:
            await self._ack(
                imp.ws, imp.rid, ok=False,
                error="no admission slot within the import window",
                error_kind="import_rejected",
            )
            return
        try:
            req = imp.svc.engine.import_generation(
                gen, kv
            )
        except Exception as err:  # noqa: BLE001 — validation is typed
            ticket.release()
            await self._ack(imp.ws, imp.rid, ok=False, error=str(err),
                            error_kind="incompatible")
            return
        self._tasks.spawn(self._serve_import(imp, req, ticket))

    def _next_event(self, req) -> dict:
        """Blocking event read with a liveness escape (runs in executor)."""
        while True:
            try:
                return req.events.get(timeout=1.0)
            except _queue.Empty:
                if self._closed or self.node._stopped:
                    return {"done": True, "result": None,
                            "error": "node stopped"}

    async def _serve_import(self, imp: _PendingImport, req, ticket) -> None:
        """Target-side pump: the imported Request's events → resume-stream
        frames back to the exporter. The ACK fires on the first event, so
        a pool-exhausted import rejects typed instead of ok-then-dying."""
        node = self.node
        rid = imp.rid
        acked = False
        prior = len(imp.gen.get("out") or [])

        async def ack_ok():
            nonlocal acked
            if not acked:
                acked = True
                await self._ack(imp.ws, rid, ok=True)
                _C_MIGRATIONS.inc(role="in", outcome="ok")
                self.stats["migrated_in"] += 1

        try:
            while True:
                ev = await asyncio.to_thread(self._next_event, req)
                if ev.get("imported"):
                    await ack_ok()
                    continue
                if ev.get("done"):
                    if ev.get("result") is None:
                        kind = ev.get("error_kind") or "import_failed"
                        detail = str(ev.get("error") or "import failed")
                        if not acked:
                            _C_MIGRATIONS.inc(role="in", outcome=kind)
                            if kind == "pool_exhausted":
                                get_recorder().incident(
                                    "migration:pool_exhausted",
                                    detail=detail, node=node.peer_id,
                                )
                            await self._ack(imp.ws, rid, ok=False,
                                            error=detail, error_kind=kind)
                        else:
                            with contextlib.suppress(Exception):
                                await node._send(imp.ws, protocol.msg(
                                    protocol.GEN_ERROR, rid=rid, error=detail,
                                ))
                        return
                    res = ev["result"]
                    await ack_ok()  # instant-finish import: ack, then done
                    ticket.note_tokens(max(0, res.new_tokens - prior))
                    with contextlib.suppress(Exception):
                        await node._send(imp.ws, protocol.msg(
                            protocol.GEN_SUCCESS,
                            rid=rid,
                            tokens=res.new_tokens,
                            finish_reason=res.finish_reason,
                            timing=dict(res.timings),
                        ))
                    return
                await ack_ok()  # fresh-submit imports have no marker event
                if ev.get("tokens"):
                    await node._send(imp.ws, protocol.msg(
                        protocol.GEN_CHUNK,
                        rid=rid,
                        text=ev.get("text") or "",
                        tokens=[int(t) for t in ev["tokens"]],
                    ))
        except Exception:  # noqa: BLE001 — exporter gone / send failed:
            # stop decoding for nobody (the row frees at the next boundary)
            req.cancelled = True
            logger.info("resume stream for %s aborted", rid, exc_info=True)
        finally:
            ticket.release()

    async def _ack(self, ws, rid, ok: bool, error: str | None = None,
                   error_kind: str | None = None) -> None:
        with contextlib.suppress(Exception):
            await self.node._send(ws, protocol.msg(
                protocol.KV_IMPORT_ACK,
                rid=rid,
                ok=ok,
                **({"error": error} if error else {}),
                **({"error_kind": error_kind} if error_kind else {}),
            ))

    def handle_ack(self, ws, data: dict) -> None:
        rid = data.get("rid")
        # the verdict must ride the connection the export went out on
        # (the target acks over the link the KV_EXPORT arrived from) —
        # a peer that learns or guesses a rid can neither fail a healthy
        # import nor fake one that never landed (fleet on_ack discipline)
        if ws is not self._rid_ws.get(rid):
            return
        fut = self._acks.get(rid)
        if fut is not None and not fut.done():
            fut.set_result({k: v for k, v in data.items() if k != "type"})

    # ------------------------------------------------------------- drain

    async def drain(self, stop: bool = False, wait: bool = True) -> dict:
        """Graceful drain (POST /admin/drain): flip to draining (admission
        503s typed, the digest advertises it, the router excludes us),
        migrate every in-flight generation to scored-healthy peers, and —
        with ``stop`` — schedule a clean GOODBYE exit once the last
        bridged stream finishes. Requests with no eligible target are
        kept local and finish here (better than erroring them).

        ``wait=True`` returns after every migrated generation COMPLETES
        (bridged stream closed — deterministic summaries for tests and
        automation with patient timeouts). ``wait=False`` returns as soon
        as the migrations are launched, with ``pending`` counting them;
        progress is visible at GET /admin/drain and the stop path still
        waits for everything."""
        node = self.node
        node.draining = True
        summary = {
            "draining": True, "migrated": 0, "reprefilled": 0,
            "forwarded": 0, "kept_local": 0, "failed": 0,
        }
        with contextlib.suppress(Exception):
            await node.gossip_telemetry()  # advertise the drain promptly
        jobs = []
        for svc in list(node.local_services.values()):
            eng = getattr(svc, "engine", None)
            # _scheduler, not .scheduler: drain must not ALLOCATE a batch
            # pool on a node that never served
            sch = getattr(eng, "_scheduler", None) if eng is not None else None
            if sch is None:
                continue
            live = sch.live_requests()
            if not live:
                continue
            if not self.migration_targets(getattr(svc, "model_name", None)):
                summary["kept_local"] += len(live)
                continue
            for req in live:
                jobs.append(self._drain_one(svc, sch, req, summary))
        if jobs:
            if wait:
                await asyncio.gather(*jobs)
            else:
                for job in jobs:
                    self._tasks.spawn(job)
                summary["pending"] = len(jobs)
        if stop:
            # NOT node._spawn: stop() cancels node tasks, and a tracked
            # task awaiting stop() would cancel itself mid-teardown
            self._stop_task = asyncio.create_task(self._stop_after_drain())
            self._stop_task.add_done_callback(log_task_exception)
        return summary

    async def _drain_one(self, svc, sch, req, summary: dict) -> None:
        snap = await asyncio.to_thread(sch.checkpoint, req)
        if snap is None:
            summary["kept_local"] += 1  # retired before the checkpoint hit
            return
        kv = snap.pop("_kv", None)
        outcome = await self._migrate_with_fallback(req, svc, snap, kv, "drain")
        key = {"ok": "migrated", "reprefill": "reprefilled",
               "forwarded": "forwarded"}.get(outcome, "failed")
        summary[key] += 1

    async def _stop_after_drain(self, timeout_s: float = 300.0) -> None:
        """Exit clean once every local row finished and every bridge
        closed: stop() sends the GOODBYE peers retire us on."""
        deadline = self.clock.monotonic() + timeout_s
        while self.clock.monotonic() < deadline:
            busy = bool(self._tasks)
            for svc in list(self.node.local_services.values()):
                eng = getattr(svc, "engine", None)
                sch = getattr(eng, "_scheduler", None) if eng is not None else None
                if sch is not None and sch.live_requests():
                    busy = True
            if not busy:
                break
            await self.clock.sleep(0.1)
        if not self.node._stopped:
            await self.node.stop()
