"""The P2P mesh runtime: WebSocket nodes with peer discovery, service
announcement, health monitoring, request routing with swarm relay, streaming
generation, and hash-verified piece transfer (reference p2p_runtime.py:33-980
reimagined; wire-compatible message set, known defects fixed — see node.py).
"""

from .node import P2PNode  # noqa: F401
from .pipeline import (  # noqa: F401 — the pipeline failure taxonomy
    StageDead,
    StageError,
    StageTimeout,
)
from .runtime import run_p2p_node  # noqa: F401
