"""P2PNode: one WebSocket mesh node.

Wire-compatible with the reference's message set (p2p_runtime.py:460-470 and
the JS bridge's subset, bridge.js:163-223): hello / peer_list / ping / pong /
service_announce / gen_request / gen_chunk / gen_success / gen_error /
gen_result / piece_request / piece_data. Reference defects deliberately fixed
(SURVEY §7 step 4):

- **gen_success vs gen_result asymmetry** (reference only resolves futures on
  gen_result, p2p_runtime.py:467,660): here the result handler accepts all of
  gen_success/gen_result/gen_error.
- **blocking execute in the event loop** (reference calls svc.execute inline,
  p2p_runtime.py:624): service execution runs in a thread executor.
- **unlocked _pending_requests** (p2p_runtime.py:794-796): guarded.
- **piece transfer stubs** (p2p_runtime.py:675-683): fully implemented, with
  binary tensor frames instead of JSON for piece payloads.

Cross-peer pipeline serving (task/result + part_load/part_forward, the
reference's worker protocol node.py:48-294) lives in meshnet/pipeline.py
(StageTaskMixin) and is wired into the dispatch table here.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import json
import logging
import os
from pathlib import Path
from typing import Any, Callable

from .. import protocol
from ..clock import Clock, resolve_clock
from ..adapters import AdapterPoolBusy, clamp_adapter_name, split_model_adapter
from ..fleet import FleetController
from ..health import HealthStore, SloTracker, build_digest, get_recorder, load_slo_config
from ..joinlink import generate_join_link, parse_join_link
from ..metrics import get_registry
from ..obs import Observatory
from ..pieces import ShardManifest
from ..router import (
    AdmissionController,
    AdmissionReject,
    PrefixTracker,
    RouterPolicy,
    TenantRegistry,
    load_admission_config,
    load_tenant_config,
    paged_pool_free_fraction,
    pool_exhaust_eta,
    static_sort,
)
from ..tracing import extract_trace, get_tracer, inject_trace, use_trace_ctx
from ..transport import Transport, resolve_transport
from ..utils import (
    MetricsAggregator,
    get_lan_ip,
    get_system_metrics,
    log_task_exception,
    new_id,
    pump_queue_until,
    sha256_hex,
)
from .migrate import MigrationManager
from .pipeline import StageDead, StageTaskMixin

logger = logging.getLogger("bee2bee_tpu.mesh")

REQUEST_TIMEOUT_S = 300.0  # reference p2p_runtime.py:831
PING_INTERVAL_S = 15.0
# dial-side redial of lost peers. The reference reconnects its worker every
# 2 s forever (node.py:286-289) and its JS bridge every 5 s (bridge.js:83-95);
# here: exponential backoff from 2 s capped at 30 s, giving up after 5 min for
# ordinary peers (a departed peer is not coming back) while bootstrap addrs
# retry forever (losing the bootstrap strands the node outside the mesh).
RECONNECT_INITIAL_S = 2.0
RECONNECT_MAX_S = 30.0
RECONNECT_WINDOW_S = 300.0
# spawned gen/task handlers per connection before the reader processes
# inline (TCP backpressure); sized past any engine/session batch depth
MAX_CONCURRENT_SERVES_PER_CONN = 32

# mesh wire accounting (metrics.py): frames/bytes by op, both directions.
# The op label is bounded by MESSAGE_TYPES (+ "tensor" for binary sends,
# whose op would cost a header decode to learn), so cardinality is fixed.
_C_FRAMES_SENT = get_registry().counter("mesh.frames_sent", "frames sent by op")
_C_BYTES_SENT = get_registry().counter("mesh.bytes_sent", "payload bytes sent by op")
_C_FRAMES_RECV = get_registry().counter(
    "mesh.frames_recv", "frames received by op"
)
_C_BYTES_RECV = get_registry().counter(
    "mesh.bytes_recv", "payload bytes received by op"
)
# per-op bound-series caches for the frame counters above (hot path —
# see _send_raw/_reader); bounded because ops are clamped to the
# protocol type set before lookup
_FRAME_SENT_INCS: dict[str, tuple] = {}
_FRAME_RECV_INCS: dict[str, tuple] = {}
_C_RELAY_HOPS = get_registry().counter(
    "mesh.relay_hops", "gen_requests forwarded through the swarm relay"
)
_C_GOSSIP_SUPPRESSED = get_registry().counter(
    "mesh.gossip_suppressed",
    "telemetry broadcasts skipped by delta suppression (unchanged digest)",
)
# generation outcome counters: the event stream the gen_error_rate SLO
# objective (health.DEFAULT_SLO_CONFIG) burns against. Counted at
# _execute_local — the one funnel every locally-served generation
# (HTTP /chat, /v1, p2p gen_request, relay target) passes through.
_C_GEN_REQUESTS = get_registry().counter(
    "gen.requests", "generations served by local services"
)
_C_GEN_ERRORS = get_registry().counter(
    "gen.errors", "locally-served generations that raised"
)

# received frame ops worth a flight-recorder ring entry: failures and
# membership changes — the events an incident bundle needs for context.
# Pings/pongs/chunks would drown the ring in weather.
_NOTABLE_OPS = frozenset(
    {protocol.GEN_ERROR, protocol.TASK_ERROR, protocol.GOODBYE, protocol.HELLO}
)


def _frame_bytes(raw: str | bytes) -> int:
    """Wire size of a RECEIVED frame: foreign peers may send non-ASCII
    JSON, where len() of the decoded str would undercount the bytes. Our
    own sends never need this — protocol.encode uses json.dumps with its
    ensure_ascii default, so outgoing text frames are pure ASCII and
    len(raw) is already the exact wire byte count."""
    return len(raw) if isinstance(raw, bytes) else len(raw.encode("utf-8"))


class P2PNode(StageTaskMixin):
    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        region: str = "default",
        node_id: str | None = None,
        announce_host: str | None = None,
        announce_port: int | None = None,
        api_port: int | None = None,
        piece_dir: str | Path | None = None,
        accept_stages: bool = True,  # advertise pipeline-stage capacity in
        # hello: failover re-placement prefers peers that said yes (set
        # False on client-only nodes that must never host model layers)
        disagg_role: str | None = None,  # "prefill" | "decode" | None —
        # disaggregated serving role (BEE2BEE_DISAGG): a prefill node
        # hands freshly prefilled generations to decode-designated peers
        # via KV migration; a decode node advertises itself as the target
        fleet_state: str | None = None,  # "standby" | None (eligible) —
        # elastic fleet role (BEE2BEE_FLEET_STATE): a standby replica is
        # connected and gossiping but router-excluded until the fleet
        # controller activates + probes it (fleet/provision.py)
        fleet_controller: bool | None = None,  # compete for the fleet
        # controller lease (BEE2BEE_FLEET=controller); every node still
        # keeps a lease view and obeys epoch-gated fleet actions
        clock: Clock | None = None,  # time seam (clock.py): None = the
        # process-global clock. Everything this node constructs (health
        # store, SLO tracker, lease, admission) inherits it, so a
        # simulation's virtual clock drives the WHOLE control plane
        transport: Transport | None = None,  # I/O seam (transport.py):
        # None = real websockets, falling back to the wscompat loopback
        # shim — the historical behavior, now as backend selection
    ):
        self.clock = resolve_clock(clock)
        self.transport = resolve_transport(transport)
        self.host = host
        self.accept_stages = accept_stages
        self.port = port
        self.region = region
        self.peer_id = node_id or new_id("node")
        self.announce_host = announce_host
        self.announce_port = announce_port
        self.api_port = api_port

        self.peers: dict[str, dict] = {}  # peer_id -> {ws, addr, metrics, ...}
        self.providers: dict[str, dict] = {}  # peer_id -> {svc_name: meta}
        self.local_services: dict[str, Any] = {}
        self.stage_runners: dict[str, Any] = {}  # model -> StageRunner (pipeline.py)
        self.stage_next: dict[str, str] = {}  # model -> next stage's peer_id (relay)
        self.stage_bursts: dict[str, dict] = {}  # ring decode accumulators (last stage)
        self.throughput = MetricsAggregator()

        # health plane (health.py): per-peer telemetry digests gossiped on
        # the ping cadence; SLO burn-rate tracking over the local registry;
        # the process-global incident flight recorder. ping_interval_s is
        # an attribute so tests shrink the cadence without monkeypatching.
        self.ping_interval_s = PING_INTERVAL_S
        # gossip delta suppression (scaling fix, bench.py fleet_sim): on
        # the monitor cadence an UNCHANGED digest is only re-broadcast
        # every gossip_refresh_ticks ticks. The HealthStore TTL is 3
        # ticks, so a refresh every 2 keeps every peer's view fresh while
        # a steady-state fleet drops ~half its telemetry frames — and, at
        # N peers per node, N× that many decodes fleet-wide. Direct
        # gossip_telemetry() calls (tests, smoke gates, fleet actions)
        # always send; only the monitor loop passes tick=True.
        self.gossip_delta_enabled = True
        self.gossip_refresh_ticks = 2
        self._gossip_fp: str | None = None
        self._gossip_ticks_since_send = 0
        # pings carry a full get_system_metrics() sample (psutil + jax
        # device introspection). One sample per TICK is the scaling fix
        # (it used to run per PEER); large in-process sims turn it off
        # entirely — FakeService control planes have nothing to report
        self.ping_metrics_enabled = True
        self.health = HealthStore(ttl_s=3 * self.ping_interval_s, clock=self.clock)
        self.recorder = get_recorder()
        # load_slo_config raises on a malformed BEE2BEE_SLO_CONFIG — a
        # mis-typed SLO must fail the node at construction, not route on
        # garbage later
        self.slo = SloTracker(
            objectives=load_slo_config(), on_trip=self._on_slo_trip,
            clock=self.clock,
        )
        # fleet observatory (obs/): retained time-series on its own
        # sampling loop + trend watchdog. The trend digest it derives
        # rides the TELEMETRY gossip (telemetry_digest), the history
        # rides /metrics/history. BEE2BEE_OBS=0 disables the sampling
        # loop (the ring stays empty; every surface reports absence);
        # BEE2BEE_OBS_CADENCE_S overrides the 5 s default.
        self.obs_enabled = (os.environ.get("BEE2BEE_OBS") or "").strip() != "0"
        try:
            obs_cadence = float(
                os.environ.get("BEE2BEE_OBS_CADENCE_S") or 0
            ) or None
        except ValueError:
            obs_cadence = None
        self.obs = Observatory(
            node=self, clock=self.clock,
            **({"cadence_s": obs_cadence} if obs_cadence else {}),
        )

        # SLO-aware front door (router/): tenant identity + budgets from
        # BEE2BEE_TENANTS, telemetry-scored provider picking, and typed
        # 429/503 admission at both ingress surfaces. All three loaders
        # raise on malformed config — same fail-at-construction contract
        # as the SLO config above.
        self.tenants = TenantRegistry(load_tenant_config())
        self.router = RouterPolicy()
        self.prefixes = PrefixTracker()
        # live generation migration (meshnet/migrate.py): graceful drain,
        # disaggregated prefill→decode handoff, migration-based failover.
        # `draining` gates admission (typed 503) and rides the telemetry
        # digest so RouterPolicy stops routing here. `drain_source`
        # ("operator" | "fleet") rides alongside it: the fleet
        # controller's orphan scan reconciles only drains ITS OWN kind
        # started — an operator's deliberate /admin/drain is never
        # undrained or converted to standby out from under them.
        self.draining = False
        self.drain_source: str | None = None
        role = (
            disagg_role
            if disagg_role is not None
            else (os.environ.get("BEE2BEE_DISAGG") or "").strip().lower()
        ) or None
        if role not in (None, "prefill", "decode", "draft"):
            raise ValueError(
                f"disagg_role must be 'prefill', 'decode', 'draft' or "
                f"unset, got {role!r}"
            )
        self.disagg_role = role
        self.migration = MigrationManager(self)
        # mesh-tiered speculative decoding (meshnet/draft.py): a draft-role
        # node hosts the DraftServer (enable_draft_server at boot); serving
        # nodes whose engine runs the mesh drafter tier get a DraftClient
        # bound in add_service
        self.draft_server = None
        self.draft_client = None
        # peer ids EVER greeted (never pruned — only their first hello
        # re-anchors the lease boot grace, see _handle_hello)
        self._greeted: set[str] = set()
        # elastic fleet control (fleet/): lease bookkeeping + the
        # epoch-gated action handler live on EVERY node; only enabled
        # controllers compete for the lease and run the decision loop
        fstate = (
            fleet_state
            if fleet_state is not None
            else (os.environ.get("BEE2BEE_FLEET_STATE") or "").strip().lower()
        ) or None
        if fstate in ("active", "eligible"):
            fstate = None
        if fstate not in (None, "standby", "warming"):
            raise ValueError(
                f"fleet_state must be 'standby', 'warming' or unset, got {fstate!r}"
            )
        self.fleet_state = fstate
        self.fleet_provision_cb = None  # async (model) -> None: boots the
        # local service on activate (weights publish→DHT→fetch in real
        # deployments — meshnet.weights.serve_model_from_mesh)
        self.fleet = FleetController(self, enabled=fleet_controller)
        self.admission = AdmissionController(
            config=load_admission_config(),
            weights=self.tenants.weights(),
            budgets=self.tenants.budgets(),
            # this node's OWN burn state (not the process-global registry):
            # the monitor loop refreshes it on the ping cadence. A WARMING
            # fleet replica reports no burn: the router excludes it from
            # all routed traffic, so the only request it legitimately
            # sees is the controller's warm-up probe — and shedding the
            # probe that would relieve a fleet-wide burn (cold-start TTFT
            # spikes trip the SLO exactly then) would deadlock scale-out.
            # Queue/pool bounds still apply, same carve-out shape as
            # migration imports.
            slo_burn=lambda: (
                0.0 if self.fleet_state == "warming"
                else self.slo.max_fast_burn()
            ),
            pool_free_fraction=paged_pool_free_fraction,
            # pool-growth forecast (engine/introspect.py): sheds
            # pool_exhausted while Retry-After still buys the client
            # something, instead of waiting for the free-fraction floor
            pool_eta=pool_exhaust_eta,
            draining=lambda: self.draining,
            clock=self.clock,
        )

        # piece store: hash -> bytes (optionally spilled to piece_dir)
        self.piece_store: dict[str, bytes] = {}
        self.piece_dir = Path(piece_dir) if piece_dir else None
        self.manifests: dict[str, ShardManifest] = {}
        # weight/adapter distribution DHT (dht.DHTNode); the runtime (or a
        # test) attaches it — None means adapter paging falls back to
        # "resident adapters only" (ensure_adapter can't fetch)
        self.dht = None
        self._adapter_fetch_locks: dict[str, asyncio.Lock] = {}

        self._server = None
        self._lock = asyncio.Lock()  # guards peers/providers
        self._pending_lock = asyncio.Lock()  # guards _pending/_chunk_cbs
        self._pending: dict[str, asyncio.Future] = {}
        # request/task id -> the ws its reply rides on: a dropped
        # connection rejects its pending futures immediately instead of
        # stranding callers until their timeout (stage chains: 120 s)
        self._pending_ws: dict[str, Any] = {}
        self._chunk_cbs: dict[str, Callable[[str], None]] = {}
        self._tasks: list[asyncio.Task] = []
        self._serving: dict[Any, int] = {}  # ws -> in-flight spawned serves
        self._stopped = False
        self.started_at: float | None = None

        # auto-reconnect state (dial side only: the listener side of a lost
        # connection waits for the dialer to come back, so exactly one end
        # redials). Attributes, not module constants, so tests can shrink
        # the backoff without monkeypatching the module.
        self.reconnect_enabled = True
        self.reconnect_initial_s = RECONNECT_INITIAL_S
        self.reconnect_max_s = RECONNECT_MAX_S
        self.reconnect_window_s = RECONNECT_WINDOW_S
        self._dial_addr_by_ws: dict[Any, str] = {}  # outbound ws -> addr dialed
        self._dialing: set[str] = set()  # addrs with a dial in flight (dedup)
        self._pid_by_ws: dict[Any, str] = {}  # ws -> peer_id (O(1) _peer_for)
        # sockets our hello has gone out on (dial-time or as a reply). A
        # hello arriving on a ws NOT in this set must be answered even if
        # the peer is already known — the sender's end of that link stays
        # unidentified until our hello lands on it (a dual-dial winner or
        # post-drop redial left mute is a permanent half-open link; found
        # by the interleaving fuzzer, simnet.fuzz churn schedule 4)
        self._helloed_ws: set = set()
        self._pong_raw: tuple | None = None  # (ts, raw) last-encoded pong
        # scheme-less host:port — the wss→ws fallback changes the scheme of
        # the addr actually dialed, and a bootstrap peer must keep its
        # retry-forever status across that downgrade
        self._bootstrap_addrs: set[str] = set()
        # addr -> goodbye time. Entries expire after reconnect_window_s:
        # suppression only needs to outlive any redial loop for that addr,
        # and an unbounded set would leak on a churny public mesh
        self._departed: dict[str, float] = {}
        self._reconnecting: set[str] = set()

    @staticmethod
    def _addr_key(addr: str) -> str:
        return addr.split("://", 1)[-1]

    def _mark_departed(self, addr: str) -> None:
        now = self.clock.time()
        self._departed = {
            a: t for a, t in self._departed.items()
            if now - t < self.reconnect_window_s
        }
        self._departed[addr] = now

    def _is_departed(self, addr: str) -> bool:
        t = self._departed.get(addr)
        return t is not None and self.clock.time() - t < self.reconnect_window_s

    def _spawn(self, coro) -> asyncio.Task:
        """Track a background task: strong ref until done, self-pruning on
        completion (a churny mesh would otherwise grow _tasks without
        bound), exception surfaced through the task log instead of dying
        with the GC's "never retrieved" warning."""
        task = asyncio.create_task(coro)
        self._tasks.append(task)
        task.add_done_callback(self._reap_task)
        return task

    def _reap_task(self, task: asyncio.Task) -> None:
        if task in self._tasks:
            self._tasks.remove(task)
        log_task_exception(task)

    # ------------------------------------------------------------ lifecycle

    @property
    def addr(self) -> str:
        host = self.announce_host or (get_lan_ip() if self.host in ("0.0.0.0", "::") else self.host)
        port = self.announce_port or self.port
        # announce_scheme: "wss" when a TLS-terminating tunnel fronts us
        # (cloudflared — tunnel.apply_to_node); peers dial wss directly
        scheme = getattr(self, "announce_scheme", None) or "ws"
        return f"{scheme}://{host}:{port}"

    def join_link(self) -> str:
        return generate_join_link(self.peer_id, [self.addr])

    async def start(self):
        # the migration scheduler hook (a foreign thread) schedules async
        # work onto this loop — capture it once at boot
        self._loop = asyncio.get_running_loop()
        self._server = await self.transport.serve(
            self._handle_connection,
            self.host,
            self.port,
            max_size=protocol.MAX_FRAME,  # reference's 32 MiB cap
        )
        if self.port == 0:  # resolve ephemeral port
            self.port = next(iter(self._server.sockets)).getsockname()[1]
        self.started_at = self.clock.time()
        # the lease boot grace counts from JOINING the mesh, not from
        # construction — a slow build (first jit compile) must not eat it
        self.fleet.lease.reset_boot_grace(self.started_at)
        self._spawn(self._monitor_loop())
        if self.obs_enabled:
            self._spawn(self.obs.run(lambda: self._stopped))
        logger.info("node %s listening on %s", self.peer_id, self.addr)
        return self

    async def stop(self):
        self._stopped = True
        # a stopping leader releases its lease (zero TTL) so a follower
        # takes over immediately instead of waiting out the lapse
        with contextlib.suppress(Exception):
            await self.fleet.release()
        # fail outstanding migrations typed before sockets go away
        self.migration.close()
        if self.draft_server is not None:
            self.draft_server.close()
        if self.draft_client is not None:
            self.draft_client.close()
        # say goodbye and close sockets FIRST — cancelling reader tasks
        # first would purge the peer table before anything gets closed,
        # leaving outbound connections dangling on the remote side
        async with self._lock:
            peers = list(self.peers.values())
            self.peers.clear()
            self.providers.clear()
            self._pid_by_ws.clear()
        for info in peers:
            with contextlib.suppress(Exception):
                await info["ws"].send(protocol.encode(protocol.msg(protocol.GOODBYE, peer_id=self.peer_id)))
                await info["ws"].close()
        # iterate copies: _spawn's done-callbacks remove finished tasks from
        # self._tasks, which would skip entries mid-iteration
        for t in list(self._tasks):
            t.cancel()
        for t in list(self._tasks):
            with contextlib.suppress(asyncio.CancelledError):
                await t
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        async with self._pending_lock:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(RuntimeError("node_stopped"))
            self._pending.clear()
            self._pending_ws.clear()
            self._chunk_cbs.clear()

    # ------------------------------------------------------------ connections

    async def _handle_connection(self, ws):
        """Inbound connection: read messages until close."""
        try:
            await self._reader(ws)
        except (self.transport.exceptions.ConnectionClosed, OSError):
            pass  # unclean peer death is normal mesh weather
        finally:
            await self._drop_peer(ws)

    async def _connect_peer(self, addr: str) -> bool:
        async with self._lock:
            if any(p.get("addr") == addr for p in self.peers.values()):
                return True
        if addr == self.addr:
            return False
        # in-flight dedup (scaling fix): during a join burst the same addr
        # arrives from several peer_lists before the first dial's hello-ack
        # registers the peer — without this, every mention opens another
        # socket and the remote logs an identity_rebind incident per extra
        # dial. The entry lives until _drop_peer (the peers-table check
        # above takes over once the ack lands), so a dropped link redials.
        if addr in self._dialing:
            return True
        self._dialing.add(addr)
        try:
            ws = await self.transport.dial(
                addr, max_size=protocol.MAX_FRAME, open_timeout=10
            )
        except Exception as e:
            self._dialing.discard(addr)  # meshlint: ignore[ML-R003] -- claim-release dedup: addr claimed before the dial await, released only by its claimant
            # wss→ws fallback mirrors the reference (p2p_runtime.py:353-361)
            if addr.startswith("wss://"):
                return await self._connect_peer("ws://" + addr[6:])
            logger.warning("connect %s failed: %s", addr, e)
            return False
        self._dial_addr_by_ws[ws] = addr  # meshlint: ignore[ML-R003] -- ws-keyed: each socket object has exactly one writer (its dialer/reader)
        self._departed.pop(addr, None)  # meshlint: ignore[ML-R003] -- last-writer-wins by design: a fresh dial resets a past goodbye
        try:
            await self._send(ws, self._hello_msg())
            self._helloed_ws.add(ws)  # meshlint: ignore[ML-R003] -- ws-keyed: each socket's hello lifecycle has one writer (its dialer or its reader), and set add/discard are atomic on the loop
        except Exception as e:
            # peer accepted the socket but died before hello (mid-shutdown):
            # treat as a failed dial, not a raise — _reconnect_loop must see
            # False and keep backing off, and the dial record must not leak
            self._dial_addr_by_ws.pop(ws, None)
            self._dialing.discard(addr)
            with contextlib.suppress(Exception):
                await ws.close()
            logger.warning("hello to %s failed: %s", addr, e)
            return False

        async def run_reader():
            try:
                await self._reader(ws)
            except (self.transport.exceptions.ConnectionClosed, OSError):
                pass  # unclean drop: _drop_peer schedules the redial
            finally:
                await self._drop_peer(ws)

        self._spawn(run_reader())
        return True

    async def connect_bootstrap(self, link_or_addr: str) -> bool:
        """Join the mesh via a ws:// addr or a join link."""
        if "://" in link_or_addr and link_or_addr.split("://")[0] not in ("ws", "wss"):
            info = parse_join_link(link_or_addr)
            for addr in info["bootstrap_addrs"]:
                if await self._connect_peer(addr):
                    self._bootstrap_addrs.add(self._addr_key(addr))
                    return True
            return False
        if await self._connect_peer(link_or_addr):
            self._bootstrap_addrs.add(self._addr_key(link_or_addr))
            return True
        return False

    async def _reader(self, ws):
        async for raw in ws:
            try:
                if isinstance(raw, bytes):
                    data, tensors = protocol.decode_binary(raw)
                    data["_tensors"] = tensors
                else:
                    data = protocol.decode(raw)
            except ValueError as e:
                logger.warning("bad frame from peer: %s", e)
                continue
            op = data.get("type")
            if op not in protocol.MESSAGE_TYPES:
                # the type string is PEER-CONTROLLED: clamping unknown ops
                # to one bucket keeps the label set (and the series table)
                # bounded no matter what a hostile peer sends
                op = "other"
            incs = _FRAME_RECV_INCS.get(op)
            if incs is None:  # bounded: op clamped above (see _send_raw)
                incs = _FRAME_RECV_INCS[op] = (
                    _C_FRAMES_RECV.bind(op=op),
                    _C_BYTES_RECV.bind(op=op),
                )
            incs[0]()
            incs[1](_frame_bytes(raw))
            if op in _NOTABLE_OPS:  # frame-op events land in the incident ring
                self.recorder.record(
                    "frame", op=op, peer=data.get("peer_id"),
                    error=data.get("error"),
                )
            try:
                await self._on_message(ws, data)
            except Exception:
                logger.exception("handler error for %s", data.get("type"))

    async def _drop_peer(self, ws):
        # migrations riding this connection fail typed NOW (the fallback
        # ladder re-prefills elsewhere instead of waiting out a timeout)
        self.migration.on_ws_drop(ws)
        # mesh drafter: re-pick another draft peer or degrade typed
        if self.draft_client is not None:
            self.draft_client.on_ws_drop(ws)
        async with self._lock:
            dead = [pid for pid, info in self.peers.items() if info["ws"] is ws]
            for pid in dead:
                self.peers.pop(pid, None)
                self.providers.pop(pid, None)
            self._pid_by_ws.pop(ws, None)
        self._helloed_ws.discard(ws)
        for pid in dead:
            logger.info("peer %s disconnected", pid)
        # fail fast anything awaiting a reply on this connection — the
        # reply can no longer arrive, and callers would otherwise hang
        # until their own timeout
        async with self._pending_lock:
            orphaned = [k for k, w in self._pending_ws.items() if w is ws]
            for key in orphaned:
                self._pending_ws.pop(key, None)
                fut = self._pending.get(key)
                if fut and not fut.done():
                    # typed: a stage chain awaiting this reply classifies
                    # the loss as a DEAD stage (StageDead subclasses
                    # RuntimeError, so non-pipeline callers are unchanged)
                    fut.set_exception(
                        StageDead("peer connection lost mid-request")
                    )
        # we dialed this connection: redial unless the peer said goodbye
        # (or we are shutting down). Inbound connections are the remote
        # dialer's job to restore.
        dial_addr = self._dial_addr_by_ws.pop(ws, None)
        if dial_addr:
            self._dialing.discard(dial_addr)  # a future dial is legitimate
        if (
            dial_addr
            and self.reconnect_enabled
            and not self._stopped
            and not self._is_departed(dial_addr)
            and dial_addr not in self._reconnecting
        ):
            self._spawn(self._reconnect_loop(dial_addr))

    async def _reconnect_loop(self, addr: str):
        """Redial `addr` with exponential backoff. Bootstrap addrs retry
        until stop(); ordinary peers give up after reconnect_window_s."""
        if addr in self._reconnecting:
            return
        self._reconnecting.add(addr)
        try:
            delay = self.reconnect_initial_s
            deadline = (
                None
                if self._addr_key(addr) in self._bootstrap_addrs
                else self.clock.time() + self.reconnect_window_s
            )
            while not self._stopped:
                await self.clock.sleep(delay)
                if self._stopped or self._is_departed(addr):
                    return
                if await self._connect_peer(addr):
                    logger.info("reconnected to %s", addr)
                    return
                if deadline is not None and self.clock.time() >= deadline:
                    logger.info("giving up reconnecting to %s", addr)
                    return
                delay = min(delay * 2, self.reconnect_max_s)
        finally:
            self._reconnecting.discard(addr)  # meshlint: ignore[ML-R003] -- claim-release dedup set: claimed before the backoff loop, released in finally

    # ------------------------------------------------------------ sending

    async def _send(self, ws, message: dict | bytes):
        raw = message if isinstance(message, bytes) else protocol.encode(message)
        # pre-encoded binary tensor frames would cost a header decode to
        # attribute; they count under one "tensor" op instead
        op = message.get("type") if isinstance(message, dict) else "tensor"
        await self._send_raw(ws, raw, op)

    async def _send_raw(self, ws, raw: str | bytes, op):
        if op not in protocol.MESSAGE_TYPES and op != "tensor":
            op = "other"  # keep the label set bounded (see _listen)
        # bound per-op series (metrics.Counter.bind): this runs per frame
        # on the wire, and re-resolving the label key each time was a
        # visible slice of a large fleet's gossip tick. Bounded: op is
        # clamped to the protocol's type set just above.
        incs = _FRAME_SENT_INCS.get(op)
        if incs is None:
            incs = _FRAME_SENT_INCS[op] = (
                _C_FRAMES_SENT.bind(op=op),
                _C_BYTES_SENT.bind(op=op),
            )
        incs[0]()
        # len(raw) IS the wire size here: bytes frames trivially, and text
        # frames because protocol.encode emits pure-ASCII JSON (see
        # _frame_bytes) — no re-encode on the send hot path
        incs[1](len(raw))
        await ws.send(raw)

    async def broadcast(self, message: dict):
        async with self._lock:
            targets = [info["ws"] for info in self.peers.values()]
        if not targets:
            return 0
        # scaling fix (sim-measured, bench.py fleet_sim): encode ONCE and
        # fan the raw frame out. The old per-peer _send re-ran
        # protocol.encode per recipient, which made each gossip tick cost
        # O(peers) JSON serializations per node — O(N²) encodes fleet-wide
        # for a frame whose bytes are identical at every peer.
        raw = protocol.encode(message)
        op = message.get("type")
        results = await asyncio.gather(
            *(self._send_raw(ws, raw, op) for ws in targets),
            return_exceptions=True,
        )
        return sum(1 for r in results if not isinstance(r, Exception))

    # ------------------------------------------------------------ hello/gossip

    def _hello_msg(self) -> dict:
        return protocol.msg(
            protocol.HELLO,
            peer_id=self.peer_id,
            addr=self.addr,
            region=self.region,
            # same gate as the ping sample: sims run engine-less control
            # planes, and a psutil snapshot's digits would make hello
            # frame sizes differ between same-seed replays
            metrics=get_system_metrics(self.throughput)
            if self.ping_metrics_enabled
            else {},
            services={n: s.get_metadata() for n, s in self.local_services.items()},
            api_port=self.api_port,
            api_host=self.announce_host or get_lan_ip(),
            accepts_stages=self.accept_stages,
        )

    # type -> handler ATTRIBUTE NAME: dispatch goes through getattr on
    # every message so chaos tooling (and tests) can monkeypatch a
    # node's `_handle_*` method and be seen immediately — while the
    # table itself is built once, not per frame (scaling fix: the old
    # per-message dict literal re-created 26 bound methods per frame,
    # a measurable slice of a large fleet's gossip tick)
    _HANDLER_NAMES = {
        protocol.HELLO: "_handle_hello",
        protocol.PEER_LIST: "_handle_peer_list",
        protocol.PING: "_handle_ping",
        protocol.PONG: "_handle_pong",
        protocol.SERVICE_ANNOUNCE: "_handle_service_announce",
        protocol.GEN_REQUEST: "_handle_gen_request",
        protocol.GEN_CHUNK: "_handle_gen_chunk",
        protocol.GEN_SUCCESS: "_handle_gen_result",
        protocol.GEN_RESULT: "_handle_gen_result",
        protocol.GEN_ERROR: "_handle_gen_result",
        protocol.PIECE_REQUEST: "_handle_piece_request",
        protocol.PIECE_DATA: "_handle_piece_data",
        protocol.PIECE_HAVE: "_handle_piece_have",
        protocol.GOODBYE: "_handle_goodbye",
        protocol.TELEMETRY: "_handle_telemetry",
        protocol.KV_EXPORT: "_handle_kv_export",
        protocol.KV_BLOCKS: "_handle_kv_blocks",
        protocol.KV_IMPORT_ACK: "_handle_kv_import_ack",
        protocol.FLEET_LEASE: "_handle_fleet_lease",
        protocol.FLEET_ACTION: "_handle_fleet_action",
        protocol.FLEET_ACK: "_handle_fleet_ack",
        protocol.ADAPTER_ANNOUNCE: "_handle_adapter_announce",
        protocol.DRAFT_REQUEST: "_handle_draft_request",
        protocol.DRAFT_RESULT: "_handle_draft_result",
        protocol.TASK: "_handle_task",
        protocol.RESULT: "_handle_result",
        protocol.TASK_ERROR: "_handle_result",
    }

    async def _on_message(self, ws, data: dict):
        name = self._HANDLER_NAMES.get(data.get("type"))
        handler = getattr(self, name) if name else None
        if handler is None:
            logger.debug("unknown message type %r", data.get("type"))
            return
        # Serving handlers run as tasks so one long generation (or stage
        # forward) never blocks this connection's reader — that's what lets
        # concurrent gen_requests batch into one PipelineSession/engine
        # batch, and lets a stage worker overlap tasks for different
        # requests (pipeline microbatching). Bounded per connection: past
        # the cap the handler runs inline, so the reader stops pulling
        # frames and TCP backpressure paces a flooding peer instead of
        # unbounded tasks/threads. Everything else stays inline:
        # gen_chunk/result ordering is part of the streaming contract.
        # FLEET_ACTION joins the spawned set: an `activate` runs the
        # node's provision hook (weight fetch — slow), and the reader
        # must keep pumping pings/telemetry meanwhile
        if data.get("type") in (
            protocol.GEN_REQUEST, protocol.TASK, protocol.FLEET_ACTION
        ):
            if self._serving.get(ws, 0) >= MAX_CONCURRENT_SERVES_PER_CONN:
                await handler(ws, data)
                return
            self._serving[ws] = self._serving.get(ws, 0) + 1

            def _served(_t, ws=ws):
                left = self._serving.get(ws, 1) - 1
                if left <= 0:
                    self._serving.pop(ws, None)
                else:
                    self._serving[ws] = left

            self._spawn(handler(ws, data)).add_done_callback(_served)
            return
        await handler(ws, data)

    async def _handle_hello(self, ws, data):
        pid = data.get("peer_id")
        if not pid or pid == self.peer_id:
            return
        known = False
        async with self._lock:
            prev = self.peers.get(pid)
            known = prev is not None
            # identity is HELLO-claimed, not cryptographic: a hello that
            # rebinds a peer id away from a LIVE connection is either a
            # simultaneous dual-dial converging or an impersonation
            # attempt (docs/ROBUSTNESS.md scopes the fleet control
            # plane's guarantees to this identity model) — allow it for
            # the former, but never silently
            live_rebind = (
                prev is not None
                and prev.get("ws") is not ws
                and self.clock.time() - prev.get("last_seen", 0.0)
                <= 3 * self.ping_interval_s
            )
            # dual-dial tie-break: when both sides dialed each other
            # concurrently, each holds one outbound and one inbound
            # connection to the same peer — and "latest hello wins" lets
            # the two ends settle on DIFFERENT sockets. Pongs echo on
            # whatever socket the ping rode, so liveness stays green,
            # but every identity-resolved inbound frame (telemetry,
            # fleet ops, tasks) resolves `_peer_for() -> None` and is
            # dropped forever: a silent half-deaf link (found by the
            # simnet split-brain scenario). Both ends instead keep the
            # connection DIALED BY THE LOWER peer id — a rule each side
            # can evaluate locally (dialed-by-me ⇔ in _dial_addr_by_ws)
            # with the same result — and close the loser.
            loser_ws = None
            if live_rebind:
                new_out = ws in self._dial_addr_by_ws
                old_out = prev.get("ws") in self._dial_addr_by_ws
                if new_out != old_out:
                    keep_out = self.peer_id < pid
                    loser_ws = ws if old_out == keep_out else prev.get("ws")
            if loser_ws is ws:
                # canonical registration survives on the previous socket;
                # this hello still proves liveness and may carry services
                prev["health"] = "online"
                prev["last_seen"] = self.clock.time()
                services = data.get("services") or {}
                if services:
                    self.providers.setdefault(pid, {}).update(services)
            else:
                if prev is not None and prev.get("ws") is not ws:
                    self._pid_by_ws.pop(prev.get("ws"), None)
                self._pid_by_ws[ws] = pid
                self.peers[pid] = {
                    "ws": ws,
                    "addr": data.get("addr"),
                    "region": data.get("region"),
                    "metrics": data.get("metrics") or {},
                    "api_port": data.get("api_port"),
                    "api_host": data.get("api_host"),
                    # failover replacement candidates rank by this (pre-taxonomy
                    # peers omit it → still eligible, just deprioritized)
                    "accepts_stages": bool(data.get("accepts_stages")),
                    "health": "online",
                    "last_seen": self.clock.time(),
                    "rtt_ms": prev.get("rtt_ms") if prev else None,
                }
                services = data.get("services") or {}
                if services:
                    self.providers.setdefault(pid, {}).update(services)
            peer_addrs = [p["addr"] for p in self.peers.values() if p.get("addr")]
        if loser_ws is not None:
            # the losing socket's dialer will short-circuit its redial:
            # _connect_peer sees the peer already registered by addr
            logger.info("dual-dial with %s converged; closing extra link", pid)
            with contextlib.suppress(Exception):
                await loser_ws.close()
        elif live_rebind:
            logger.warning(
                "hello rebinds %s away from a live connection", pid
            )
            self.recorder.incident(
                "mesh:identity_rebind",
                detail=f"hello re-registered {pid} over a new connection "
                       "while its previous link was live",
                node=self.peer_id,
            )
        if not known:
            if pid not in self._greeted:
                # first contact with a NEVER-seen peer re-anchors the
                # lease boot grace (while no lease has ever been
                # observed): a node whose bootstrap dial stalled past
                # one TTL after start() must not claim the instant it
                # finally joins — it owes the incumbent's gossip one
                # full TTL of listening first. The ever-greeted set
                # keeps a flapping link (drop + re-hello faster than
                # one TTL) from deferring the first election forever.
                self._greeted.add(pid)
                self.fleet.lease.reset_boot_grace()
        # reply whenever OUR hello has never gone out on THIS socket:
        # first contact, or a hello from an already-known peer over a new
        # link (a dual-dial winner we only ever helloed on the loser we
        # closed, or a redial after a one-sided drop). Replying only on
        # first contact leaves those links mute — the other end never
        # receives our hello, never registers us, and the link stays
        # half-open forever while this end keeps serving a live
        # registration (found by the interleaving fuzzer: simnet.fuzz
        # churn scenario, a dual-dial loser's FIN racing the winner's
        # hello). No ping-pong: our reply lands on a socket the peer has
        # already helloed on, so the peer stays quiet.
        if not known or ws not in self._helloed_ws:
            self._helloed_ws.add(ws)
            await self._send(ws, self._hello_msg())
            await self._send(ws, protocol.msg(protocol.PEER_LIST, peers=peer_addrs))

    async def _handle_peer_list(self, ws, data):
        # prefilter against already-connected / in-flight addrs ONCE per
        # list (scaling fix): during a join burst every edge handshake
        # carries a full peer list, so spawning a dial task per mention —
        # each redoing an O(peers) scan under the lock — is O(N³) work
        # fleet-wide. One set build per list makes the steady-state cost
        # of a redundant peer list O(N) and spawns only genuinely new dials.
        addrs = data.get("peers") or []
        async with self._lock:
            connected = {p.get("addr") for p in self.peers.values()}
        # connect concurrently off the reader task: a serial await here would
        # stall all message processing on this connection for up to
        # open_timeout per dead address in a churned peer list
        for addr in addrs:
            if (
                addr
                and addr != self.addr
                and addr not in connected
                and addr not in self._dialing
            ):
                self._spawn(self._connect_peer_quiet(addr))

    async def _connect_peer_quiet(self, addr: str):
        with contextlib.suppress(Exception):
            await self._connect_peer(addr)

    async def _handle_ping(self, ws, data):
        pid = await self._peer_for(ws)
        if pid and data.get("metrics"):
            async with self._lock:
                if pid in self.peers:
                    self.peers[pid]["metrics"] = data["metrics"]
                    self.peers[pid]["last_seen"] = self.clock.time()
        # a pong's bytes are a pure function of the echoed ts, and a ping
        # burst from one sender tick shares its ts — one-slot encode cache
        # (cache-miss cost is a tuple compare, so the unsynchronized
        # production case loses nothing)
        ts = data.get("ts")
        cached = self._pong_raw
        if cached is None or cached[0] != ts:
            cached = (ts, protocol.encode(protocol.msg(protocol.PONG, ts=ts)))
            self._pong_raw = cached
        await self._send_raw(ws, cached[1], protocol.PONG)

    async def _handle_pong(self, ws, data):
        pid = await self._peer_for(ws)
        ts = data.get("ts")
        if pid and isinstance(ts, (int, float)):
            rtt = (self.clock.time() - ts) * 1000.0
            async with self._lock:
                if pid in self.peers:
                    self.peers[pid]["rtt_ms"] = round(rtt, 2)
                    self.peers[pid]["health"] = "online"
                    self.peers[pid]["last_seen"] = self.clock.time()

    async def _handle_service_announce(self, ws, data):
        svc, meta = data.get("service"), data.get("meta") or {}
        pid = await self._peer_for(ws)
        if pid and svc:
            async with self._lock:
                self.providers.setdefault(pid, {})[svc] = meta

    async def _handle_goodbye(self, ws, data):
        # clean departure: suppress the redial loop for this address —
        # EXCEPT for bootstrap addrs, whose goodbye is normally a graceful
        # restart (stop() sends GOODBYE): losing the bootstrap forever on
        # every deploy would strand the node outside the mesh
        addr = self._dial_addr_by_ws.get(ws)
        if addr and self._addr_key(addr) not in self._bootstrap_addrs:
            self._mark_departed(addr)
        # a clean departure also retires the peer's health digest at once;
        # an UNCLEAN drop keeps it until the staleness TTL, so a flapping
        # peer's last reading survives the reconnect window
        pid = await self._peer_for(ws)
        if pid:
            self.health.drop(pid)
        await self._drop_peer(ws)

    # ------------------------------------------------------------ health plane

    def telemetry_digest(self) -> dict:
        """This node's gossip digest: the metrics-registry summary
        (health.build_digest) plus node-local context the registry can't
        see — peer RTTs and the latest SLO brief."""
        digest = build_digest()
        # sync snapshot of the peer table (same pattern as peer_for_addr):
        # safe on the loop thread, and list() guards executor callers
        rtts = {
            pid: info.get("rtt_ms")
            for pid, info in list(self.peers.items())
            if info.get("rtt_ms") is not None
        }
        if rtts:
            digest["peer_rtt_ms"] = rtts
        slo = self.slo.brief()
        if slo:
            digest["slo"] = slo
        # prefix-cache locality hints (router/prefixmap.py): the chained
        # leading-block hashes of recently-served prompts, so peers can
        # route repeat prefixes here and hit the CoW prefix cache
        prefixes = self.prefixes.advertised()
        if prefixes:
            digest["prefix_hashes"] = prefixes
        # KV-pool identity (ISSUE 12 drive-by): cache dtype + effective
        # capacity ride the digest, KEYED BY SERVICE (a node may host a
        # bf16-pool and an int8-pool engine side by side), so
        # /mesh/health and the router can see WHICH peers run the
        # doubled int8 pool — the raw block-count gauges alone can't say
        # what a block's bytes buy
        kv_info = {}
        for name, svc in list(self.local_services.items()):
            eng = getattr(svc, "engine", None)
            if eng is not None:
                try:
                    kv_info[str(name)] = eng.kv_info
                except Exception:  # noqa: BLE001 — telemetry must not
                    # fail the gossip loop on an engine mid-teardown
                    pass
        if kv_info:
            digest["kv"] = kv_info
        # adapter residency (adapters/): the router's placement input —
        # a peer already holding the requested adapter skips the fetch +
        # pool churn, so RouterPolicy credits it (never past an outright
        # loaded node, same tolerance discipline as the prefix bonus)
        adapter_info = {}
        for name, svc in list(self.local_services.items()):
            eng = getattr(svc, "engine", None)
            if eng is not None:
                try:
                    resident = eng.resident_adapters()
                except Exception:  # noqa: BLE001 — telemetry never throws
                    resident = []
                if resident:
                    adapter_info[str(name)] = resident
        if adapter_info:
            digest["adapters"] = adapter_info
        # drain state rides the digest so RouterPolicy excludes draining
        # peers on the same gossip the rest of the scoring reads; the
        # disagg role is how prefill nodes find decode-designated targets
        if self.draining:
            digest["draining"] = True
            if self.drain_source:
                digest["drain_source"] = self.drain_source
        if self.disagg_role:
            digest["disagg_role"] = self.disagg_role
        # elastic fleet (fleet/): a standby/warming replica advertises
        # its state so routers and the migration plane exclude it, and
        # controller-eligible nodes advertise themselves so takeover
        # ranks are computed over the LIVE controller set
        if self.fleet_state:
            digest["fleet_state"] = self.fleet_state
        if self.fleet.enabled:
            digest["fleet_controller"] = True
        # trend digest (obs/): window mean + relative slope + anomaly
        # flags per retained series — what the router's degrading
        # penalty and the controller's pool forecast read off peers
        trend = self.obs.trend_digest()
        if trend is not None:
            digest["trend"] = trend
        return digest

    async def gossip_telemetry(self, tick: bool = False) -> int:
        """Broadcast this node's digest as one TELEMETRY frame; returns the
        number of peers reached. Rides the ping cadence (_monitor_loop) but
        is callable directly (tests, smoke gates) for deterministic gossip.

        tick=True applies delta suppression (see __init__): an unchanged
        digest is skipped until gossip_refresh_ticks ticks have passed
        since the last send. The fingerprint excludes the "ts" stamp and
        the per-peer RTT block — both are measurement noise that changes
        on EVERY tick (RTT jitters by design), and either would defeat
        the comparison forever. Peers still get fresh RTTs on each
        refresh tick, so RTT staleness is bounded at gossip_refresh_ticks
        ticks; anything operationally actionable (counters, gauges,
        histograms, draining/fleet state) re-gossips immediately. The
        "trend" block is excluded for the same reason — its window means
        drift a little every sample by construction, and including it
        would re-defeat the suppression the fleet_sim bench exists to
        hold — so trend staleness at peers is bounded by the same
        gossip_refresh_ticks deal RTTs get."""
        digest = self.telemetry_digest()
        if tick and self.gossip_delta_enabled:
            body = {
                k: v for k, v in digest.items()
                if k not in ("ts", "peer_rtt_ms", "trend")
            }
            fp = json.dumps(body, sort_keys=True, default=str)
            if (
                fp == self._gossip_fp
                and self._gossip_ticks_since_send + 1 < self.gossip_refresh_ticks
            ):
                self._gossip_ticks_since_send += 1
                _C_GOSSIP_SUPPRESSED.inc()
                return 0
            self._gossip_fp = fp
            self._gossip_ticks_since_send = 0
        return await self.broadcast(
            protocol.msg(
                protocol.TELEMETRY,
                peer_id=self.peer_id,
                digest=digest,
            )
        )

    async def _handle_telemetry(self, ws, data):
        # identity comes from the CONNECTION (hello handshake), not the
        # frame's peer_id claim — a peer cannot overwrite another peer's
        # digest by lying in the payload
        pid = await self._peer_for(ws)
        digest = data.get("digest")
        if pid and isinstance(digest, dict):
            self.health.update(pid, digest)

    def _on_slo_trip(self, objective, entry: dict) -> None:
        """SloTracker trip hook: snapshot an incident bundle. The kind is
        per-objective (bounded by the configured objective list) so one
        burning objective's cooldown never masks a different one."""
        self.recorder.incident(
            "slo:" + objective.name,
            detail=f"burn rate fast={entry.get('burn_rate_fast')} "
                   f"slow={entry.get('burn_rate_slow')}",
            node=self.peer_id,
            extra=entry,
        )

    def peer_for_addr(self, addr: str) -> str | None:
        """peer_id for a dialed OR announced address (scheme-insensitive).
        A dialed peer may announce a different host than we dialed
        (loopback dial vs LAN announce), so both are checked.

        Sync on purpose (callers aren't async) — safe because a sync
        method on the loop thread can't interleave with the async
        mutators; the list() snapshot keeps it safe even if a future
        refactor calls this from an executor thread."""
        key = self._addr_key(addr)
        for pid, info in list(self.peers.items()):
            dial = self._dial_addr_by_ws.get(info.get("ws"))
            if dial and self._addr_key(dial) == key:
                return pid
            if info.get("addr") and self._addr_key(info["addr"]) == key:
                return pid
        return None

    async def _peer_for(self, ws) -> str | None:
        # reverse map maintained by _handle_hello/_drop_peer/stop
        # (scaling fix): this runs for EVERY ping/pong/telemetry receipt,
        # and the old linear peers scan made each gossip tick O(peers²)
        # per node — the dominant per-tick cost at fleet scale
        async with self._lock:
            pid = self._pid_by_ws.get(ws)
            if pid is not None and self.peers.get(pid, {}).get("ws") is ws:
                return pid
            # slow path: direct writes into node.peers (tests, chaos
            # tooling) bypass the map — fall back to the scan and repair
            for pid, info in self.peers.items():
                if info["ws"] is ws:
                    self._pid_by_ws[ws] = pid
                    return pid
        return None

    # ------------------------------------------------------------ services

    def add_service(self, svc) -> None:
        self.local_services[svc.name] = svc
        # ONE tenant-weight source: an engine-backed service's scheduler
        # adopts this node's resolved registry (its constructor only
        # env-seeds the same config; a runtime-replaced TenantRegistry
        # would otherwise drift from the engine's WDRR weights)
        sched = getattr(getattr(svc, "engine", None), "scheduler", None)
        if sched is not None and hasattr(sched, "set_tenant_weights"):
            sched.set_tenant_weights(self.tenants.weights())
        # live-migration hook: drain/handoff/pool-pressure rows leave via
        # this node's migration plane (no-op for engine-less services)
        self.migration.wire_scheduler(svc)
        # mesh drafter tier (BEE2BEE_DRAFTER=mesh): bind the scheduler's
        # MeshDrafter to this node's transport so drafts stream from a
        # draft-role peer (wire_scheduler above already forced the lazy
        # scheduler into existence for engine-backed services)
        md = getattr(sched, "mesh_drafter", None)
        if md is not None:
            from .draft import DraftClient

            if self.draft_client is None:
                self.draft_client = DraftClient(self)
            self.draft_client.bind(md)

    def enable_draft_server(self, model: str, spec_tokens: int = 6,
                            **kw) -> None:
        """Host the drafter program on this node (the `draft` disagg
        role). Loads the draft model NOW so a bad spec fails the node
        typed at boot, never at the first frame."""
        from .draft import DraftServer

        self.draft_server = DraftServer(
            self, model, spec_tokens=spec_tokens, **kw
        )

    async def _handle_draft_request(self, ws, data):
        srv = self.draft_server
        if srv is None:
            # not a draft node (stale gossip routed here): typed refusal
            # — the client books a failure and degrades to its local tier
            if not data.get("done"):
                await self._send(ws, protocol.msg(
                    protocol.DRAFT_RESULT,
                    rid=str(data.get("rid") or ""), error="no_drafter",
                ))
            return
        pid = await self._peer_for(ws)
        srv.submit(ws, pid or "?", data)

    async def _handle_draft_result(self, ws, data):
        if self.draft_client is not None:
            self.draft_client.deliver(data)

    async def announce_service(self, svc) -> int:
        self.add_service(svc)
        return await self.broadcast(
            protocol.msg(protocol.SERVICE_ANNOUNCE, service=svc.name, meta=svc.get_metadata())
        )

    async def announce_adapters(self, svc) -> int:
        """Broadcast the service's CURRENT adapter residency (hot-swap
        fetch/evict) so peers' provider tables track the per-adapter
        model names without waiting for a re-hello."""
        meta = svc.get_metadata()
        return await self.broadcast(protocol.msg(
            protocol.ADAPTER_ANNOUNCE,
            peer_id=self.peer_id,
            service=svc.name,
            adapters=meta.get("adapters") or [],
            models=meta.get("models") or [],
        ))

    async def _handle_adapter_announce(self, ws, data):
        # like telemetry: identity comes from the CONNECTION, not the
        # frame's peer_id claim
        pid = await self._peer_for(ws)
        svc = data.get("service")
        names = data.get("adapters")
        if not pid or not svc or not isinstance(names, list):
            return
        async with self._lock:
            meta = self.providers.setdefault(pid, {}).setdefault(str(svc), {})
            meta["adapters"] = [str(n) for n in names[:64]]
            models = data.get("models")
            if isinstance(models, list) and models:
                meta["models"] = [str(m) for m in models[:256]]

    async def ensure_adapter(self, svc, name: str) -> bool:
        """Resolve one adapter for an engine-backed service: already
        resident → True; otherwise PAGE it in over the mesh (DHT manifest
        → sha256-verified pieces → AdapterPool, LRU-evicting a cold
        adapter) without restarting the engine, then re-announce
        residency. False = unknown adapter (the caller answers the typed
        404 / unknown_adapter). AdapterPoolBusy propagates — every slot
        pinned by in-flight rows is BACKPRESSURE on a valid adapter, and
        collapsing it to False would tell the client a published adapter
        does not exist (a 404 an SDK will never retry). Concurrent
        requests for the same adapter share one fetch via a per-name
        lock."""
        engine = getattr(svc, "engine", None)
        if engine is None or getattr(engine, "adapter_pool", None) is None:
            return False
        if engine.has_adapter(name):
            return True
        if self.dht is None:
            return False
        lock = self._adapter_fetch_locks.setdefault(name, asyncio.Lock())
        try:
            async with lock:
                if engine.has_adapter(name):
                    return True
                base = engine.model_cfg.name
                from ..adapters.distrib import (
                    UnknownAdapterManifest,
                    fetch_adapter,
                )

                try:
                    with get_tracer().span(
                        "adapter.fetch", adapter=name, model=base
                    ):
                        adapters, lcfg = await fetch_adapter(
                            self, self.dht, base, name,
                            model_cfg=engine.model_cfg,
                        )
                        # load on an executor: the device write +
                        # validation must not park the mesh reader loop
                        await asyncio.get_running_loop().run_in_executor(
                            None,
                            lambda: engine.load_adapter(name, adapters, lcfg),
                        )
                except UnknownAdapterManifest:
                    # nobody published this name: the typed-404 case, not
                    # an infrastructure failure — no incident
                    logger.info("adapter %r: no manifest on the DHT", name)
                    return False
                except AdapterPoolBusy:
                    # transient: every slot has in-flight rows. Not a
                    # fetch failure (no incident) and NOT unknown — the
                    # caller maps it onto the pool_exhausted shed
                    raise
                except Exception as e:  # noqa: BLE001 — fetch/verify/pool
                    self.recorder.incident(
                        "adapter:fetch_failed",
                        detail=str(e),
                        node=self.peer_id,
                        extra={"adapter": name, "model": base},
                    )
                    logger.warning("adapter %r fetch failed: %s", name, e)
                    return False
                self._spawn(self.announce_adapters(svc))
                return True
        finally:
            # never let wire-chosen names accumulate state: the lock only
            # matters while a fetch is in flight. Waiters still hold their
            # reference to this lock object; a post-pop arrival creating a
            # fresh lock can at worst duplicate a fetch (benign — the
            # in-lock has_adapter re-check absorbs it).
            if not lock.locked():
                self._adapter_fetch_locks.pop(name, None)

    def list_providers(self, model: str | None = None) -> list[dict]:
        """Flatten local + remote providers (reference p2p_runtime.py:687-721)."""
        out = []
        for name, svc in self.local_services.items():
            meta = svc.get_metadata()
            out.append({"provider_id": self.peer_id, "service": name, "local": True, **meta})
        for pid, svcs in self.providers.items():
            peer = self.peers.get(pid, {})
            for name, meta in svcs.items():
                out.append(
                    {
                        "provider_id": pid,
                        "service": name,
                        "local": False,
                        "_latency": peer.get("rtt_ms"),
                        "health": peer.get("health"),
                        **meta,
                    }
                )
        if model:
            out = [
                p for p in out
                if any(model.lower() in m.lower() or m.lower() in model.lower() for m in p.get("models", []))
            ]
        return out

    def pick_provider(
        self,
        model: str | None = None,
        prompt: str | None = None,
        exclude=(),
        remote_only: bool = False,
        adapter: str | None = None,
    ) -> dict | None:
        """Telemetry-scored provider pick (router/policy.py): queue-wait,
        batch-fill headroom, paged-pool pressure, SLO burn state, RTT and
        prompt-prefix locality from the gossiped health digests. Falls
        back to the reference's static cheapest-then-lowest-latency sort
        when NO candidate has a fresh digest — the regime where nothing
        better is knowable (and where the old ``_latency or 1e9`` wart is
        contained: a never-pinged peer under the scored path gets the
        explicit unknown tier instead of permanent last place)."""
        cands = self.list_providers(model)
        if remote_only:
            cands = [p for p in cands if not p["local"]]
        if exclude:
            cands = [p for p in cands if p["provider_id"] not in exclude]
        if not cands:
            return None
        fresh = self.health.fresh()
        if not any(p["provider_id"] in fresh for p in cands if not p["local"]):
            # no live telemetry about any remote candidate: legacy sort
            # (local-only candidate lists land here too — the local node
            # needs no digest to pick itself)
            return static_sort(cands)
        local_digest = (
            self.telemetry_digest()
            if any(p["local"] for p in cands) else None
        )
        winner, _decision = self.router.pick(
            cands, fresh, local_digest=local_digest, prompt=prompt,
            adapter=adapter,
        )
        return winner

    # ------------------------------------------------------------ generation

    async def request_generation(
        self,
        provider_id: str,
        prompt: str,
        model: str | None = None,
        max_new_tokens: int = 2048,
        temperature: float = 0.7,
        stream: bool = False,
        on_chunk: Callable[[str], None] | None = None,
        timeout: float = REQUEST_TIMEOUT_S,
        extra: dict | None = None,  # sampling knobs (top_k/top_p/penalties):
        # ride the wire as plain message keys — the reference ignores
        # unknown keys, so the frame stays wire-compatible
        tenant: str | None = None,  # per-tenant identity (router/): the
        # serving node's admission bills the same tenant the gateway did
    ) -> dict:
        params = {
            "prompt": prompt,
            "max_new_tokens": max_new_tokens,
            "temperature": temperature,
            **(extra or {}),
        }
        # self-request shortcut (reference p2p_runtime.py:761-787)
        if provider_id == self.peer_id:
            svc = self.local_service_for(model)
            if svc is None:
                raise RuntimeError(f"no local service for model {model!r}")
            return await self._execute_local(svc, params, stream, on_chunk)

        async with self._lock:
            info = self.peers.get(provider_id)
            svc_name = self._remote_service_name(provider_id, model)
        if info is None:
            raise RuntimeError(f"unknown provider {provider_id!r}")

        rid = new_id("req")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        async with self._pending_lock:
            self._pending[rid] = fut
            self._pending_ws[rid] = info["ws"]
            if on_chunk:
                self._chunk_cbs[rid] = on_chunk
        try:
            with get_tracer().span(
                "gen.p2p", provider=provider_id, model=model, rid=rid
            ):
                # inject_trace: the remote hop parents its spans under this
                # gen.p2p span (relay hops chain the context onward), so
                # /trace?trace_id= fragments stitch into one timeline
                await self._send(
                    info["ws"],
                    inject_trace(protocol.msg(
                        protocol.GEN_REQUEST,
                        rid=rid,
                        prompt=prompt,
                        model=model,
                        svc=svc_name,
                        max_new_tokens=max_new_tokens,
                        max_tokens=max_new_tokens,  # reference reads this key
                        temperature=temperature,
                        stream=bool(stream or on_chunk),
                        # omitted when absent (the sampling-knob
                        # convention): a null tenant is wire noise the
                        # receiver would only clamp away
                        **({"tenant": tenant} if tenant is not None else {}),
                        **(extra or {}),
                    )),
                )
                result = await self.clock.wait_for(fut, timeout)
                # raise inside the span so remote-error results count as
                # span errors in /trace, same as timeouts do
                if isinstance(result, dict) and result.get("error"):
                    if result.get("error_kind"):
                        # a typed admission shed must SURVIVE the hop: the
                        # gateway maps this back onto 429/503+Retry-After
                        # instead of a 500 that defeats client backoff
                        raise AdmissionReject(
                            result["error_kind"],
                            float(result.get("retry_after_s") or 0.0),
                            detail=str(result["error"]),
                        )
                    raise RuntimeError(result["error"])
        except asyncio.TimeoutError:
            raise RuntimeError("request_timed_out")
        finally:
            async with self._pending_lock:
                self._pending.pop(rid, None)
                self._pending_ws.pop(rid, None)
                self._chunk_cbs.pop(rid, None)
        return result

    def local_service_for(self, model: str | None):
        """Fuzzy-match a local service for `model`; None when a specific
        model was asked for and nothing matches (the caller then falls back
        to the mesh — answering for the wrong model would be worse)."""
        if not model:
            return next(iter(self.local_services.values()), None)
        for svc in self.local_services.values():
            models = svc.get_metadata().get("models", [])
            if any(model.lower() in m.lower() or m.lower() in model.lower() for m in models):
                return svc
        return None

    @staticmethod
    def adapter_capable(svc) -> bool:
        """Can this service serve `<base>:<adapter>` model ids? Only an
        engine-backed service with an AdapterPool can — the gate that
        scopes the colon grammar: backends whose OWN model ids contain
        colons (ollama tags like "llama3:8b") must keep serving them
        verbatim."""
        engine = getattr(svc, "engine", None)
        return engine is not None and getattr(engine, "adapter_pool", None) is not None

    def service_advertising(self, model) -> object | None:
        """The local service whose metadata lists `model` VERBATIM (case-
        insensitive), or None. Deliberately stricter than the fuzzy
        local_service_for: deciding that a colon-containing id is the
        backend's own tag (not our adapter grammar) must not fuzzy-match
        "tiny-llama:acme" onto a pool-less "tiny-llama" service and
        silently serve the plain base."""
        if not isinstance(model, str):
            return None
        for svc in self.local_services.values():
            models = svc.get_metadata().get("models", [])
            if any(
                isinstance(m, str) and m.lower() == model.lower()
                for m in models
            ):
                return svc
        return None

    def _remote_service_name(self, provider_id: str, model: str | None) -> str:
        svcs = self.providers.get(provider_id, {})
        if model:
            for name, meta in svcs.items():
                if model in meta.get("models", []):
                    return name
        return next(iter(svcs), "tpu")

    async def _execute_local(self, svc, params, stream, on_chunk) -> dict:
        # SLO event accounting wraps the whole serve: every locally-served
        # generation (HTTP, /v1, p2p, relay target) funnels through here
        _C_GEN_REQUESTS.inc()
        # prefix-locality advertisement (router/prefixmap.py): what this
        # node just served is what its prefix cache plausibly holds
        self.prefixes.note(params.get("prompt"))
        try:
            return await self._execute_local_inner(svc, params, stream, on_chunk)
        except Exception:
            _C_GEN_ERRORS.inc()
            raise

    async def _execute_local_inner(self, svc, params, stream, on_chunk) -> dict:
        loop = asyncio.get_running_loop()
        with get_tracer().span(
            "gen.local", service=svc.name, stream=bool(stream or on_chunk)
        ) as span:
            # copy_context so engine spans emitted inside the worker thread
            # keep this span as their parent (run_in_executor alone drops
            # contextvars)
            ctx = contextvars.copy_context()
            if stream or on_chunk:
                import json as _json

                text_parts: list[str] = []
                final: dict = {}  # real accounting off the done line

                def feed(line: str, threadsafe: bool):
                    obj = _json.loads(line)
                    if obj.get("text"):
                        text_parts.append(obj["text"])
                        if on_chunk:
                            if threadsafe:
                                loop.call_soon_threadsafe(on_chunk, obj["text"])
                            else:
                                on_chunk(obj["text"])
                    if obj.get("done"):
                        if obj.get("tokens") is not None:
                            final["tokens"] = int(obj["tokens"])
                            final["cost"] = float(obj.get("cost") or 0.0)
                        if obj.get("timing") is not None:
                            final["timing"] = obj["timing"]
                    if obj.get("status") == "error":
                        raise RuntimeError(obj.get("message", "stream error"))

                def run_stream():
                    for line in svc.execute_stream(params):
                        feed(line, threadsafe=True)

                t0 = self.clock.time()
                stream_async = getattr(svc, "execute_stream_async", None)
                if stream_async is not None:
                    # loop-native service (e.g. PipelineService): no
                    # executor thread blocked per request — the session
                    # coroutine lives on this same loop
                    async for line in stream_async(params):
                        feed(line, threadsafe=False)
                else:
                    await loop.run_in_executor(None, ctx.run, run_stream)
                span.attrs["chunks"] = len(text_parts)
                # mesh-level throughput: real token counts ride the done
                # line when the service reports them; chars/4 (the
                # reference's estimate) is only the fallback
                est = final.get("tokens") or (
                    max(1, len("".join(text_parts)) // 4) if text_parts else 0
                )
                if est:
                    self.throughput.record(est, self.clock.time() - t0)
                out = {
                    "text": "".join(text_parts),
                    "tokens": final.get("tokens"),
                    "cost": final.get("cost"),
                    "streamed": True,
                }
                if final.get("timing") is not None:
                    out["timing"] = final["timing"]
                return out
            exec_async = getattr(svc, "execute_async", None)
            if exec_async is not None:
                result = await exec_async(params)
            else:
                result = await loop.run_in_executor(None, ctx.run, svc.execute, params)
            span.attrs["tokens"] = result.get("tokens")
            # feed the node's advertised throughput (rides pings/registry/
            # metrics — the reference FABRICATES this number, we measure
            # it). `is not None`: a 0-token completion (instant EOS,
            # max_new_tokens=0) still counts as a served request.
            if result.get("tokens") is not None:
                self.throughput.record(
                    int(result["tokens"]),
                    float(result.get("latency_ms") or 0) / 1000.0,
                )
            return result

    async def _handle_gen_request(self, ws, data):
        # adopt the requester's trace context: the gen.local / relay
        # gen.p2p spans below parent under the ORIGINATING request, so
        # every node's /trace?trace_id= fragment joins one timeline
        # (absent/malformed ctx from old peers is a no-op)
        with use_trace_ctx(extract_trace(data)):
            await self._serve_gen_request(ws, data)

    async def _serve_gen_request(self, ws, data):
        rid = data.get("rid") or data.get("task_id")
        model = data.get("model")
        # multi-adapter serving: the adapter rides either the explicit
        # `adapter` key or the "<base>:<name>" model form — one parser
        # (adapters.split_model_adapter) for every surface. The wire
        # claim is CLAMPED: an oversized/exotic string — via EITHER
        # carrier — answers the typed unknown_adapter below; it must
        # never mint metric series or DHT keys, and never silently
        # degrade to serving the plain base model.
        base_model, model_adapter = split_model_adapter(model)
        svc = self.local_services.get(data.get("svc", "")) or self.local_service_for(base_model)
        if (
            data.get("adapter") is None and model_adapter is not None
            and not self.adapter_capable(svc)
        ):
            # the colon can only mean OUR adapter grammar on a pooled
            # engine; a backend advertising the full id verbatim (ollama
            # "llama3:8b") keeps serving it whole. No verbatim match
            # keeps the split, so a pool-less engine still answers the
            # typed unknown_adapter below instead of silently serving
            # the plain base.
            verbatim = self.service_advertising(model)
            if verbatim is not None:
                svc, base_model, model_adapter = verbatim, model, None
        raw_adapter = (
            data.get("adapter")
            if data.get("adapter") is not None else model_adapter
        )
        adapter = None
        if raw_adapter is not None:
            adapter = clamp_adapter_name(raw_adapter)
            if adapter is None:
                if data.get("adapter") is None and svc is None:
                    # model-derived half on a pure relay hop: not ours
                    # to judge — forward the original id whole below and
                    # let the serving node parse it (a backend's own
                    # tags may use chars our adapter names forbid)
                    pass
                else:
                    with contextlib.suppress(Exception):
                        await self._send(ws, protocol.msg(
                            protocol.GEN_ERROR, rid=rid,
                            error="unknown_adapter: malformed adapter name",
                            error_kind="unknown_adapter",
                        ))
                    return
        mnt = data.get("max_new_tokens")
        if mnt is None:  # explicit 0 must stay 0 ("or" would turn it into 2048)
            mnt = data.get("max_tokens")
        params = {
            "prompt": data.get("prompt", ""),
            "max_new_tokens": 2048 if mnt is None else int(mnt),
            "temperature": data.get("temperature", 0.7),
        }
        protocol.copy_sampling(data, params)
        if svc is not None and adapter:
            # resolve (or PAGE IN over the DHT) before admission: a slot
            # must not sit occupied through a multi-second piece fetch
            try:
                resolved = await self.ensure_adapter(svc, adapter)
            except AdapterPoolBusy as busy:
                # valid adapter, saturated pool: the pool_exhausted shed
                # (retryable 503 twin), NEVER unknown_adapter — a 404
                # would tell the client a published adapter is gone
                with contextlib.suppress(Exception):
                    await self._send(ws, protocol.msg(
                        protocol.GEN_ERROR, rid=rid,
                        error=f"adapter_pool_busy: {busy}",
                        error_kind="pool_exhausted",
                        retry_after_s=self.admission.config.shed_retry_after_s,
                    ))
                return
            if not resolved:
                with contextlib.suppress(Exception):
                    await self._send(ws, protocol.msg(
                        protocol.GEN_ERROR, rid=rid,
                        error=f"unknown_adapter: {adapter!r} is not resident "
                              "and could not be fetched",
                        error_kind="unknown_adapter",
                    ))
                return
            params["adapter"] = adapter
        if svc is not None:
            # p2p ingress admission (router/admission.py): the frame's
            # tenant claim is clamped to a CONFIGURED name — an arbitrary
            # wire string must not mint queues or metric series
            tenant = self.tenants.clamp(data.get("tenant"))
            params["tenant"] = tenant
            try:
                ticket = await self.admission.acquire(
                    tenant, cost_tokens=params["max_new_tokens"]
                )
            except AdmissionReject as rej:
                # typed shed over the wire: error_kind + retry_after_s ride
                # the GEN_ERROR frame (declared in analysis/schema.py), the
                # p2p twin of the HTTP 429/503 + Retry-After contract
                with contextlib.suppress(Exception):
                    await self._send(ws, protocol.msg(
                        protocol.GEN_ERROR, rid=rid,
                        error=f"admission_rejected: {rej.detail}",
                        error_kind=rej.kind,
                        retry_after_s=rej.retry_after_s,
                    ))
                return
            try:
                if data.get("stream"):
                    send_q: asyncio.Queue = asyncio.Queue()

                    def on_chunk(text):
                        send_q.put_nowait(text)

                    task = asyncio.create_task(
                        self._execute_local(svc, params, True, on_chunk)
                    )
                    result = await pump_queue_until(
                        task,
                        send_q,
                        lambda text: self._send(
                            ws, protocol.msg(protocol.GEN_CHUNK, rid=rid, text=text)
                        ),
                    )
                    ticket.note_tokens(result.get("tokens") or 0)
                    await self._send(ws, protocol.msg(protocol.GEN_SUCCESS, rid=rid, **result))
                else:
                    result = await self._execute_local(svc, params, False, None)
                    ticket.note_tokens(result.get("tokens") or 0)
                    await self._send(ws, protocol.msg(protocol.GEN_SUCCESS, rid=rid, **result))
            except Exception as e:
                # a failed generation is a typed incident: snapshot the ring
                # + this request's trace (we're under use_trace_ctx, so the
                # recorder picks the trace_id off the contextvar)
                self.recorder.incident(
                    "gen_error", detail=str(e), node=self.peer_id
                )
                # the peer may be the reason we failed (died mid-stream):
                # best-effort error reply, no second exception
                with contextlib.suppress(Exception):
                    await self._send(
                        ws, protocol.msg(protocol.GEN_ERROR, rid=rid, error=f"local_error: {e}")
                    )
            finally:
                ticket.release()
            return
        # swarm relay: one extra hop through another provider
        # (reference p2p_runtime.py:634-655) — telemetry-scored like any
        # other pick, never bouncing the request back to its requester
        requester = await self._peer_for(ws)
        cand = self.pick_provider(
            model,
            prompt=params["prompt"],
            exclude={requester} if requester else (),
            remote_only=True,
            adapter=adapter,
        )
        if cand is None:
            await self._send(
                ws,
                protocol.msg(
                    protocol.GEN_RESULT, rid=rid, error="consensus_deadlock: no_node_available"
                ),
            )
            return
        _C_RELAY_HOPS.inc()
        relay_extra = protocol.copy_sampling(params, {})
        if adapter and data.get("adapter") is not None:
            # an EXPLICIT adapter claim survives the relay hop explicitly
            # — the serving node clamps/resolves it against ITS OWN pool.
            # A model-string-derived half stays inside the forwarded
            # model id instead: this relay can't know whether the far
            # node reads "llama3:8b" as its own tag or as our grammar.
            relay_extra["adapter"] = adapter
        try:
            if data.get("stream"):
                # relay the STREAM too: chunks from the far provider are
                # re-framed under our rid as they arrive — without this a
                # relayed stream request returns empty text while the
                # provider does the full paid generation
                relay_q: asyncio.Queue = asyncio.Queue()
                task = asyncio.create_task(
                    self.request_generation(
                        cand["provider_id"],
                        params["prompt"],
                        model=model,
                        max_new_tokens=params["max_new_tokens"],
                        temperature=params["temperature"],
                        stream=True,
                        on_chunk=relay_q.put_nowait,
                        extra=relay_extra,
                        # the ORIGINAL claim, unclamped: the serving node
                        # clamps against its own tenant config
                        tenant=data.get("tenant"),
                    )
                )
                result = await pump_queue_until(
                    task,
                    relay_q,
                    lambda text: self._send(
                        ws, protocol.msg(protocol.GEN_CHUNK, rid=rid, text=text)
                    ),
                )
            else:
                result = await self.request_generation(
                    cand["provider_id"],
                    params["prompt"],
                    model=model,
                    max_new_tokens=params["max_new_tokens"],
                    temperature=params["temperature"],
                    extra=relay_extra,
                    tenant=data.get("tenant"),
                )
            # the inner result carries its own rid — replace it with ours
            fwd = {k: v for k, v in result.items() if k not in ("rid", "task_id", "type")}
            await self._send(ws, protocol.msg(protocol.GEN_RESULT, rid=rid, **fwd))
        except AdmissionReject as rej:
            # the relay TARGET shed: forward the typed rejection intact
            # (error_kind + retry_after_s on GEN_RESULT, schema-declared)
            # so the originating gateway still answers 429/503 +
            # Retry-After instead of a generic relay failure
            await self._send(ws, protocol.msg(
                protocol.GEN_RESULT, rid=rid,
                error=f"relay_admission_rejected: {rej.detail}",
                error_kind=rej.kind,
                retry_after_s=rej.retry_after_s,
            ))
        except Exception as e:
            await self._send(
                ws, protocol.msg(protocol.GEN_RESULT, rid=rid, error=f"relay_link_failure: {e}")
            )

    async def _handle_gen_chunk(self, ws, data):
        rid = data.get("rid") or data.get("task_id")
        # migration resume streams ride GEN_CHUNK under the migration rid:
        # the bridge feeds the ORIGINAL request's event queue (token ids,
        # not just text) — checked first, it owns its rids exclusively
        if self.migration.feed_chunk(rid, data):
            return
        async with self._pending_lock:
            cb = self._chunk_cbs.get(rid)
        if cb and data.get("text"):
            cb(data["text"])

    async def _handle_gen_result(self, ws, data):
        rid = data.get("rid") or data.get("task_id")
        if self.migration.feed_result(rid, data):
            return
        async with self._pending_lock:
            fut = self._pending.get(rid)
        if fut and not fut.done():
            payload = {k: v for k, v in data.items() if k not in ("type",)}
            fut.set_result(payload)

    # ------------------------------------------------------- live migration

    async def _handle_kv_export(self, ws, data):
        # adopt the exporter's trace context so the import/resume spans
        # stitch under the original request's timeline
        with use_trace_ctx(extract_trace(data)):
            await self.migration.handle_export(ws, data)

    async def _handle_kv_blocks(self, ws, data):
        await self.migration.handle_blocks(ws, data)

    async def _handle_kv_import_ack(self, ws, data):
        self.migration.handle_ack(ws, data)

    async def begin_drain(self, stop: bool = False, wait: bool = True,
                          source: str = "operator") -> dict:
        """Graceful drain (POST /admin/drain): see MigrationManager.drain.
        ``source`` stamps WHO started it ("operator" | "fleet") into the
        gossiped digest — the fleet controller reconciles only its own."""
        self.drain_source = source
        return await self.migration.drain(stop=stop, wait=wait)

    def end_drain(self) -> None:
        """Cancel the draining state (fleet rollback / operator undo):
        admission re-opens and the next gossip drops the digest flag.
        Migrations already launched complete harmlessly — their rows
        left; new work lands here again."""
        self.draining = False
        self.drain_source = None

    # ------------------------------------------------------- elastic fleet

    async def _handle_fleet_lease(self, ws, data):
        await self.fleet.on_lease(ws, data)

    async def _handle_fleet_action(self, ws, data):
        await self.fleet.on_action(ws, data)

    async def _handle_fleet_ack(self, ws, data):
        await self.fleet.on_ack(ws, data)

    # ------------------------------------------------------------ pieces

    def store_piece(self, data: bytes) -> str:
        digest = sha256_hex(data)
        self.piece_store[digest] = data
        if self.piece_dir:
            from ..pieces import save_pieces

            save_pieces([data], self.piece_dir)
        return digest

    def get_piece(self, digest: str) -> bytes | None:
        data = self.piece_store.get(digest)
        if data is None and self.piece_dir:
            try:
                from ..pieces import load_piece

                data = load_piece(self.piece_dir, digest)
            except (OSError, ValueError):
                return None
        return data

    async def request_piece(self, peer_id: str, digest: str, timeout: float = 60.0) -> bytes:
        """Fetch a piece from a peer; hash-verified before returning."""
        async with self._lock:
            info = self.peers.get(peer_id)
        if info is None:
            raise RuntimeError(f"unknown peer {peer_id!r}")
        rid = new_id("piece")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        async with self._pending_lock:
            self._pending[rid] = fut
            self._pending_ws[rid] = info["ws"]
        try:
            await self._send(
                info["ws"], protocol.msg(protocol.PIECE_REQUEST, rid=rid, hash=digest)
            )
            result = await self.clock.wait_for(fut, timeout)
        finally:
            async with self._pending_lock:
                self._pending.pop(rid, None)
                self._pending_ws.pop(rid, None)
        if result.get("error"):
            raise RuntimeError(result["error"])
        data = bytes(result["_tensors"]["data"].tobytes())
        if sha256_hex(data) != digest:
            raise ValueError(f"piece {digest[:12]} failed hash verification")
        return data

    async def _handle_piece_request(self, ws, data):
        import numpy as np

        rid, digest = data.get("rid"), data.get("hash")
        blob = self.get_piece(digest) if digest else None
        if blob is None:
            await self._send(
                ws, protocol.msg(protocol.PIECE_DATA, rid=rid, hash=digest, error="piece_not_found")
            )
            return
        frame = protocol.encode_binary(
            protocol.msg(protocol.PIECE_DATA, rid=rid, hash=digest),
            {"data": np.frombuffer(blob, dtype=np.uint8)},
        )
        await self._send(ws, frame)

    async def _handle_piece_data(self, ws, data):
        rid = data.get("rid")
        async with self._pending_lock:
            fut = self._pending.get(rid)
        if fut and not fut.done():
            fut.set_result(data)

    async def _handle_piece_have(self, ws, data):
        pid = await self._peer_for(ws)
        if pid:
            async with self._lock:
                self.peers.get(pid, {}).setdefault("pieces", set()).update(
                    data.get("hashes") or []
                )

    # ------------------------------------------------------------ monitoring

    async def _monitor_loop(self):
        last_counts: dict[str, float] = {}
        while not self._stopped:
            try:
                await self.clock.sleep(self.ping_interval_s)
                async with self._lock:
                    targets = list(self.peers.items())
                now = self.clock.time()
                # one metrics sample + one encode per TICK, not per peer:
                # get_system_metrics walks psutil and jax devices (slow),
                # and the ping frame's bytes are identical at every peer
                # (scaling fix, bench.py fleet_sim). Sims with hundreds of
                # engine-less control planes disable the sample outright.
                metrics = (
                    get_system_metrics(self.throughput)
                    if self.ping_metrics_enabled and targets
                    else None
                )
                raw_ping = protocol.encode(protocol.msg(
                    protocol.PING,
                    ts=now,
                    **({"metrics": metrics} if metrics is not None else {}),
                ))
                for pid, info in targets:
                    try:
                        await self._send_raw(info["ws"], raw_ping, protocol.PING)
                    except Exception:
                        await self._drop_peer(info["ws"])
                async with self._lock:
                    for pid, info in self.peers.items():
                        if now - info.get("last_seen", now) > 3 * self.ping_interval_s:
                            info["health"] = "unreachable"
                # health plane, on the same cadence: evaluate SLO burn
                # rates (refreshes the slo.* gauges, fires trip incidents),
                # gossip the digest, and drop a metric-delta ring event
                self.slo.evaluate()
                await self.gossip_telemetry(tick=True)
                self._record_metric_deltas(last_counts)
                # elastic fleet control loop, same cadence: lease renew/
                # claim + (leaders only) one hysteresis-guarded decision
                await self.fleet.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("monitor loop error")

    def _record_metric_deltas(self, last: dict[str, float]) -> None:
        """One per-tick flight-recorder event with the counter deltas that
        tell an incident's story ('what changed in the last interval') —
        never throws, like everything feeding the ring.

        The counter list spans every subsystem that can STAR in an
        incident: the PR 5/6 serving funnel, plus (ISSUE 15 fix — these
        predated the ring) the quantized-KV pool churn, the adapter
        pool's load/evict/request traffic, the fleet controller's
        decision/action stream, live migrations, and the retrace
        sentinel's compile/storm counters — so a bundle from any of those
        subsystems carries its own state, not just the gen funnel's. A
        compact gauge snapshot rides alongside (pool occupancy, adapter
        residency, admission pressure, fleet role): gauges have no
        deltas, but an incident reader needs the levels at the tick."""
        try:
            reg = get_registry()
            deltas: dict[str, float] = {}
            for name in (
                "gen.requests", "gen.errors", "engine.tokens_generated",
                "mesh.relay_hops", "pipeline.recoveries",
                # spec decode (PR 4) + quantized-KV pool CoW churn (PR 12)
                "engine.spec_drafted", "engine.spec_accepted",
                # adapter pool (PR 14)
                "adapter.pool_loads", "adapter.pool_evicted",
                "adapter.requests",
                # fleet controller (PR 13) + live migration (PR 9)
                "fleet.decisions", "fleet.actions", "mesh.migrations",
                # admission front door (PR 7)
                "admission.shed",
                # engine economics (ISSUE 15)
                "engine.compiles", "engine.retrace_storms",
            ):
                m = reg.get(name)
                if m is None:
                    continue
                cur = m.total()
                d = cur - last.get(name, 0.0)
                last[name] = cur
                if d:
                    deltas[name] = d
            gauges: dict[str, float] = {}
            for name in (
                "engine.paged_blocks_in_use", "engine.paged_blocks_free",
                "adapter.pool_resident",
                "admission.inflight", "admission.queued",
                "fleet.leader", "fleet.eligible_replicas",
                "engine.hbm_headroom_frac", "engine.mfu",
            ):
                g = reg.get(name)
                if g is None or not g.series():
                    continue  # subsystem not running / gauge cleared
                gauges[name] = g.value()
            if deltas or gauges:
                self.recorder.record(
                    "metrics_delta", deltas=deltas, gauges=gauges
                )
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    # ------------------------------------------------------------ status

    def status(self) -> dict:
        return {
            "peer_id": self.peer_id,
            "addr": self.addr,
            "region": self.region,
            "uptime_s": round(self.clock.time() - self.started_at, 1) if self.started_at else 0,
            "peers": len(self.peers),
            "local_services": list(self.local_services),
            "providers": sum(len(v) for v in self.providers.values()),
            "pieces": len(self.piece_store),
            "metrics": get_system_metrics(self.throughput),
        }
