"""The mesh draft leg: a cheap peer hosts ONLY the drafter model.

``BEE2BEE_DISAGG=draft`` extends the disaggregation role vocabulary
(prefill/decode — meshnet/migrate.py) with a third program placement:
the drafter is a distinct, much smaller program than the target, so it
can live on a node with no TPU headroom at all and still pay for itself
— every accepted draft token saves the TARGET a full decode step.

Wire protocol (protocol.DRAFT_REQUEST / DRAFT_RESULT, declared in
analysis/schema.py):

- request {rid, base, tokens, k, model}: ``base`` is the context length
  the server already holds for ``rid`` and ``tokens`` the delta to
  append — steady state ships only the accepted tokens from the last
  verify verdict (a handful of ints), so frames stay tiny. ``base=0``
  resends the full context (timeout recovery, server restart);
  {rid, done:true} frees the server row at retirement.
- result {rid, pos, draft}: ``pos`` is the context length the draft
  continues from — the client (engine/spec.MeshDrafter) drops a result
  whose pos no longer matches its context, so a slow draft for an old
  position can never corrupt a row. {rid, reprime:true} asks the client
  for a full resend; {rid, error} is the server's typed failure.

PIPELINING: the client requests the NEXT draft inside the verify
verdict (MeshDrafter.observe), so the round trip runs concurrently with
the target's own next decode/verify step; propose_batch only consumes
results that already arrived. A missing draft is PENDING (the row skips
one step), a timed-out one is a miss against the row's probe budget,
and a dead peer degrades every mesh row to the LOCAL drafter tier —
typed, logged once, zero dropped generations (the scheduler's
_spec_degrade_dead). The decode loop never blocks on the network.

Server ordering: draft_request frames for a row mutate its context, so
they must apply in arrival order — the handler enqueues and ONE worker
task drains the queue sequentially, running the jit draft call in an
executor thread so the node's event loop (pings, gossip, other rows'
frames) never stalls behind a drafter forward.
"""

from __future__ import annotations

import asyncio
import logging
import threading

from .. import protocol
from ..metrics import get_registry

logger = logging.getLogger("bee2bee_tpu.draft")

_REG = get_registry()
_C_DRAFT_SERVED = _REG.counter(
    "mesh.draft_served", "draft_request frames served by this draft node"
)
_C_DRAFT_ERRORS = _REG.counter(
    "mesh.draft_errors", "draft_request frames answered with a typed error"
)


class _SrvReq:
    """Stable-identity context holder: DraftModel keys its KV slots off
    id(req) and reads .ids/.out_ids — one of these per server row keeps
    the slot pinned across requests while the ctx list grows in place."""

    __slots__ = ("ids", "out_ids")

    def __init__(self):
        self.ids: list[int] = []
        self.out_ids: list[int] = []   # always empty; ctx lives in ids


class _SrvRow:
    __slots__ = ("req", "last_used")

    def __init__(self):
        self.req = _SrvReq()
        self.last_used = 0.0


class DraftServer:
    """Server side of the draft role: per-(peer, rid) context rows feeding
    one resident DraftModel. Constructed at boot (enable_draft_server) so
    a bad drafter spec fails the node typed at startup, not at the first
    frame."""

    def __init__(self, node, model: str, spec_tokens: int = 6,
                 max_rows: int = 8, dtype: str = "float32",
                 seed: int = 0, checkpoint_path: str | None = None,
                 drafter=None):
        from ..engine.drafter import DraftModel

        self.node = node
        self.spec_tokens = spec_tokens
        self.drafter = drafter or DraftModel(
            model, spec_tokens=spec_tokens, batch=max_rows,
            # the drafter's own positional capacity is the real bound; the
            # DraftModel caps against its config's max_seq_len internally
            target_max_seq_len=1 << 20,
            dtype=dtype, seed=seed, checkpoint_path=checkpoint_path,
        )
        self.max_rows = max_rows
        self._rows: dict[tuple[str, str], _SrvRow] = {}
        self._queue: asyncio.Queue = asyncio.Queue()
        self._worker: asyncio.Task | None = None
        self._closed = False
        if drafter is None:
            # compile the prime/draft roots NOW: the first real frame must
            # pay network latency only — a multi-second first-draft jit
            # compile would make every early draft stale on arrival
            warm = _SrvReq()
            warm.ids = list(range(1, 17))
            self.drafter.propose_batch([(0, warm)])
            self.drafter.forget(warm)

    # ---------------------------------------------------------- intake
    def submit(self, ws, pid: str, msg: dict) -> None:
        """Handler entry (event loop): enqueue for the ordered worker."""
        if self._closed:
            return
        if self._worker is None or self._worker.done():
            self._worker = self.node._spawn(self._drain())
        self._queue.put_nowait((ws, pid, msg))

    async def _drain(self):
        while not self._closed:
            ws, pid, msg = await self._queue.get()
            try:
                await self._serve_one(ws, pid, msg)
            except Exception:  # noqa: BLE001 — one bad frame must not
                logger.exception("draft request failed")  # kill the worker

    def _evict_lru(self) -> None:
        if len(self._rows) < self.max_rows:
            return
        key = min(self._rows, key=lambda k: self._rows[k].last_used)
        row = self._rows.pop(key)
        self.drafter.forget(row.req)

    async def _serve_one(self, ws, pid: str, msg: dict):
        rid = str(msg.get("rid") or "")
        key = (pid, rid)
        if msg.get("done"):
            row = self._rows.pop(key, None)
            if row is not None:
                self.drafter.forget(row.req)
            return
        base = int(msg.get("base") or 0)
        tokens = [int(t) for t in (msg.get("tokens") or [])]
        row = self._rows.get(key)
        if row is None:
            if base != 0:
                # a delta for a row we don't hold (restart, LRU eviction):
                # ask for the full context instead of drafting off garbage
                await self.node._send(ws, protocol.msg(
                    protocol.DRAFT_RESULT, rid=rid, reprime=True
                ))
                return
            self._evict_lru()
            row = _SrvRow()
            self._rows[key] = row
        ctx = row.req.ids
        if base == 0:
            # full (re)send. Context is append-only on the client (prompt
            # + accepted tokens), so replacing in place keeps the KV
            # frontier the DraftModel tracks for this row valid.
            ctx[:] = tokens
        elif base == len(ctx):
            ctx.extend(tokens)
        else:
            # delta baseline mismatch (a lost frame out of order): typed
            # resync rather than silently drafting from a wrong context
            await self.node._send(ws, protocol.msg(
                protocol.DRAFT_RESULT, rid=rid, reprime=True
            ))
            return
        row.last_used = self.node.clock.monotonic()
        pos = len(ctx)
        loop = asyncio.get_running_loop()
        try:
            # the jit forward runs off-loop; the worker awaits it, so rows
            # are still served strictly in order
            out = await loop.run_in_executor(
                None, self.drafter.propose_batch, [(0, row.req)]
            )
            draft = out.get(0) or []
            _C_DRAFT_SERVED.inc()
        except Exception as e:  # noqa: BLE001 — typed error to the client
            logger.exception("drafter compute failed")
            _C_DRAFT_ERRORS.inc()
            await self.node._send(ws, protocol.msg(
                protocol.DRAFT_RESULT, rid=rid, error=str(e) or "draft_failed"
            ))
            return
        await self.node._send(ws, protocol.msg(
            protocol.DRAFT_RESULT, rid=rid, pos=pos,
            draft=[int(t) for t in draft],
        ))

    def close(self) -> None:
        self._closed = True
        if self._worker is not None:
            self._worker.cancel()
            self._worker = None
        for row in self._rows.values():
            self.drafter.forget(row.req)
        self._rows.clear()
        self.drafter.close()


class DraftClient:
    """Client side: binds the scheduler's MeshDrafter(s) to the mesh.

    The send path runs on the SCHEDULER THREAD (MeshDrafter._submit):
    it picks the draft peer from the freshest telemetry digests, then
    hops onto the node's event loop for the actual frame send —
    fire-and-forget, the MeshDrafter's own deadline ladder covers every
    loss mode. Results and peer-loss notifications flow back in on the
    event loop (_handle_draft_result / _drop_peer)."""

    def __init__(self, node):
        self.node = node
        self._lock = threading.Lock()
        self._drafters: list = []          # bound MeshDrafter instances
        self._peer_ws = None               # cached (pid, ws)

    def bind(self, mesh_drafter) -> None:
        with self._lock:
            if mesh_drafter not in self._drafters:
                self._drafters.append(mesh_drafter)
        mesh_drafter.attach_transport(self._send_fn)

    # ------------------------------------------------- peer selection
    def _pick_peer(self):
        """(pid, ws) of a live draft-role peer, or None. Reads gossip
        state from the scheduler thread: health.fresh() locks internally
        and the peers dict is snapshotted (same discipline as
        node.peer_for_addr)."""
        fresh = self.node.health.fresh()
        peers = dict(self.node.peers)
        for pid, d in fresh.items():
            if not isinstance(d, dict) or d.get("disagg_role") != "draft":
                continue
            info = peers.get(pid)
            if info is not None and info.get("ws") is not None:
                return pid, info["ws"]
        return None

    def _send_fn(self, payload: dict) -> bool:
        """MeshDrafter transport hook (scheduler thread). False = the
        frame can never leave (no loop / no draft peer) — the drafter
        flips dead and the scheduler degrades rows to the local tier."""
        loop = getattr(self.node, "_loop", None)
        if loop is None or loop.is_closed() or self.node._stopped:
            return False
        with self._lock:
            peer = self._peer_ws
        if peer is None:
            peer = self._pick_peer()
            if peer is None:
                return False
            with self._lock:
                self._peer_ws = peer
        msg = protocol.msg(protocol.DRAFT_REQUEST, **payload)
        try:
            loop.call_soon_threadsafe(self._post, peer[1], msg)
        except RuntimeError:
            return False                    # loop closed under us
        return True

    def _post(self, ws, msg: dict) -> None:
        # on the event loop: a failed/slow send surfaces as a client-side
        # deadline miss, never as an exception into the scheduler
        self.node._spawn(self.node._send(ws, msg))

    # ------------------------------------------------- loop-side events
    def deliver(self, msg: dict) -> None:
        with self._lock:
            drafters = list(self._drafters)
        for d in drafters:
            d.deliver(msg)                  # unknown rids are ignored

    def on_ws_drop(self, ws) -> None:
        """_drop_peer hook: our draft peer's connection died. Re-pick if
        another draft-role peer is live; otherwise flip every bound
        drafter dead (typed "peer_lost") so rows degrade immediately
        instead of riding out their timeouts."""
        with self._lock:
            cached = self._peer_ws
            if cached is None or cached[1] is not ws:
                return
            self._peer_ws = None
        repick = self._pick_peer()
        if repick is not None:
            with self._lock:
                self._peer_ws = repick
            return
        with self._lock:
            drafters = list(self._drafters)
        for d in drafters:
            d.peer_lost()

    def close(self) -> None:
        with self._lock:
            self._drafters.clear()
            self._peer_ws = None
