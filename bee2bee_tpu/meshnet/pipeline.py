"""Cross-peer pipeline serving: coordinator + worker task handlers.

BASELINE config 4 (zephyr-7b split across two peers). The reference's
coordinator never survived in its repo — only the worker loop (reference
node.py:48-294) and the protocol constants; this module implements BOTH
halves the TPU-native way:

- Workers hold a StageRunner (layers [a, b) on their own mesh) and answer
  `task` messages of kind part_load / part_forward / part_release
  (protocol.TASK_PART_LOAD/TASK_PART_FORWARD). Hidden states travel as
  binary tensor frames (protocol.encode_binary), not JSON float lists.
- `PipelineCoordinator` drives a generation: prompt ids → stage 0 →
  hidden → stage 1 → ... → logits → sample host-side → feed the token
  back through the chain at the next offset. Per-stage KV caches live on
  the workers, so each decode step moves only [B, 1, D] activations.

The coordinator is itself a mesh peer: it speaks to stage workers over
the same WebSocket connections the gossip/generation traffic uses.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from .. import protocol
from ..utils import new_id

logger = logging.getLogger("bee2bee_tpu.pipeline")

DEFAULT_STEP_TIMEOUT = 120.0


# --------------------------------------------------------------- node mixin


class StageTaskMixin:
    """Task-protocol handlers mixed into P2PNode (kept separate so the
    mesh core stays readable; node.py wires _handle_task/_handle_result
    into its dispatch table)."""

    def add_stage_runner(self, runner) -> None:
        """Host a pipeline stage (StageRunner) on this node."""
        self.stage_runners[runner.model_cfg.name] = runner

    async def _handle_task(self, ws, data):
        kind = data.get("kind")
        task_id = data.get("task_id")

        async def fail(error: str):
            await self._send(
                ws, protocol.msg(protocol.TASK_ERROR, task_id=task_id, error=error)
            )

        try:
            if kind == protocol.TASK_PART_LOAD:
                await self._task_part_load(ws, data)
            elif kind == protocol.TASK_PART_FORWARD:
                await self._task_part_forward(ws, data)
            elif kind == "part_release":
                runner = self.stage_runners.get(data.get("model"))
                if runner is not None:
                    runner.release(data.get("request_id"))
                await self._send(
                    ws, protocol.msg(protocol.RESULT, task_id=task_id, ok=True)
                )
            else:
                await fail(f"unknown task kind {kind!r}")
        except Exception as e:  # noqa: BLE001 — worker must answer, not die
            logger.exception("task %s failed", kind)
            await fail(f"{type(e).__name__}: {e}")

    async def _task_part_load(self, ws, data):
        from ..engine.stage_runner import StageRunner

        task_id = data.get("task_id")
        loop = asyncio.get_running_loop()
        runner = await loop.run_in_executor(
            None,
            lambda: StageRunner(
                data["model"],
                n_stages=int(data["n_stages"]),
                stage=int(data["stage"]),
                checkpoint_path=data.get("checkpoint_path"),
                max_seq_len=int(data.get("max_seq_len", 2048)),
                dtype=data.get("dtype", "bfloat16"),
                rng_seed=int(data.get("rng_seed", 0)),
            ),
        )
        self.add_stage_runner(runner)
        await self._send(
            ws, protocol.msg(protocol.RESULT, task_id=task_id, ok=True, info=runner.info)
        )

    async def _task_part_forward(self, ws, data):
        task_id = data.get("task_id")
        runner = self.stage_runners.get(data.get("model"))
        if runner is None:
            raise RuntimeError(f"no stage loaded for model {data.get('model')!r}")
        x = data["_tensors"]["x"]
        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(
            None,
            lambda: runner.forward(
                data["request_id"], x, int(data.get("offset", 0))
            ),
        )
        frame = protocol.encode_binary(
            protocol.msg(protocol.RESULT, task_id=task_id, ok=True),
            {"out": out},
        )
        await self._send(ws, frame)

    async def _handle_result(self, ws, data):
        """RESULT / TASK_ERROR → resolve the matching pending future."""
        task_id = data.get("task_id")
        async with self._pending_lock:
            fut = self._pending.get(task_id)
        if fut and not fut.done():
            fut.set_result(data)

    async def run_stage_task(
        self,
        peer_id: str,
        kind: str,
        fields: dict,
        tensors: dict | None = None,
        timeout: float = DEFAULT_STEP_TIMEOUT,
    ) -> dict:
        """Send one task to a peer and await its RESULT (tensors included
        under '_tensors'). Raises on TASK_ERROR or timeout."""
        async with self._lock:
            info = self.peers.get(peer_id)
        if info is None:
            raise RuntimeError(f"unknown peer {peer_id!r}")
        task_id = new_id("task")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        async with self._pending_lock:
            self._pending[task_id] = fut
        message = protocol.msg(protocol.TASK, kind=kind, task_id=task_id, **fields)
        try:
            if tensors:
                await self._send(info["ws"], protocol.encode_binary(message, tensors))
            else:
                await self._send(info["ws"], message)
            result = await asyncio.wait_for(fut, timeout=timeout)
        finally:
            async with self._pending_lock:
                self._pending.pop(task_id, None)
        if result.get("type") == protocol.TASK_ERROR or result.get("error"):
            raise RuntimeError(result.get("error") or "task failed")
        return result


# ------------------------------------------------------------- coordinator


class PipelineCoordinator:
    """Drive generation across stage workers (reference contrast:
    node.py:249-277 chains hf_part_forward hops; here the chain carries a
    KV-cached decode loop with host-side sampling at the coordinator)."""

    def __init__(
        self,
        node,
        model: str,
        stage_peers: list[str],  # peer_ids in stage order (stage i = peers[i])
        max_seq_len: int = 2048,
        dtype: str = "bfloat16",
        rng_seed: int = 0,
    ):
        self.node = node
        self.model = model
        self.stage_peers = stage_peers
        self.max_seq_len = max_seq_len
        self.dtype = dtype
        self.rng_seed = rng_seed

    async def load(
        self, checkpoint_path: str | None = None, timeout: float = 600.0
    ) -> list[dict]:
        """part_load every stage concurrently; returns their stage infos.
        `timeout` covers checkpoint read + compile per stage (a 7B half
        takes minutes — far beyond the per-step default)."""
        results = await asyncio.gather(
            *(
                self.node.run_stage_task(
                    peer,
                    protocol.TASK_PART_LOAD,
                    {
                        "model": self.model,
                        "n_stages": len(self.stage_peers),
                        "stage": s,
                        "max_seq_len": self.max_seq_len,
                        "dtype": self.dtype,
                        "rng_seed": self.rng_seed,
                        "checkpoint_path": checkpoint_path,
                    },
                    timeout=timeout,
                )
                for s, peer in enumerate(self.stage_peers)
            )
        )
        return [r.get("info", {}) for r in results]

    async def _chain(self, request_id: str, x: np.ndarray, offset: int) -> np.ndarray:
        """ids/hidden through every stage; returns last stage's logits."""
        for peer in self.stage_peers:
            result = await self.node.run_stage_task(
                peer,
                protocol.TASK_PART_FORWARD,
                {"model": self.model, "request_id": request_id, "offset": offset},
                tensors={"x": x},
            )
            x = result["_tensors"]["out"]
        return x

    async def release(self, request_id: str) -> None:
        await asyncio.gather(
            *(
                self.node.run_stage_task(
                    peer,
                    "part_release",
                    {"model": self.model, "request_id": request_id},
                )
                for peer in self.stage_peers
            ),
            return_exceptions=True,
        )

    async def generate(
        self,
        prompt_ids: list[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        eos_token_id: int | None = None,
        on_token=None,
    ) -> list[int]:
        """Greedy/temperature generation across the pipeline. Returns new
        token ids (stops at eos_token_id when given)."""
        rid = new_id("ppreq")
        rng = np.random.default_rng(abs(hash(rid)) % (2**32))
        # left-truncate over-long prompts to what the stage caches can hold
        # (the engine's serving behavior: keep the most recent context)
        budget = self.max_seq_len - 1 - max(1, min(max_new_tokens, self.max_seq_len - 1))
        prompt_ids = list(prompt_ids)[-max(budget, 1):]
        n = len(prompt_ids)
        if n + max_new_tokens >= self.max_seq_len:
            max_new_tokens = max(0, self.max_seq_len - 1 - n)
        if max_new_tokens <= 0:
            return []
        # pow2 prompt bucket bounds worker recompiles; pad K/V past n is
        # overwritten by decode exactly when it enters the causal window
        # (same trick as the engine's bucketed prefill)
        bucket = 16
        while bucket < n:
            bucket *= 2
        bucket = min(bucket, self.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = prompt_ids
        out: list[int] = []
        try:
            logits = await self._chain(rid, padded, offset=0)
            tok = self._sample(logits[0, n - 1], temperature, rng)
            offset = n
            while True:
                if eos_token_id is not None and tok == eos_token_id:
                    break
                out.append(tok)
                if on_token is not None:
                    on_token(tok)
                if len(out) >= max_new_tokens:
                    break
                logits = await self._chain(
                    rid, np.asarray([[tok]], np.int32), offset=offset
                )
                offset += 1
                tok = self._sample(logits[0, -1], temperature, rng)
        finally:
            await self.release(rid)
        return out

    @staticmethod
    def _sample(logits: np.ndarray, temperature: float, rng) -> int:
        if temperature is None or temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(temperature, 1e-6)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(p), p=p))
